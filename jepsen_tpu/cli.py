"""Command-line interface — upstream ``jepsen/src/jepsen/cli.clj``
(SURVEY.md §2.1, L10): ``run`` (execute a test), ``serve`` (results
browser), plus this framework's ``recheck`` (offline re-analysis of a
stored history — the checkpoint/resume path of SURVEY.md §5) and
``bench`` shortcut.

``python -m jepsen_tpu run --suite register --mode sloppy ...``
"""
from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import Any, Callable, Dict, Mapping, Optional, Sequence


def _add_common(ap: argparse.ArgumentParser) -> None:
    """The upstream shared option set (``--nodes``, ``--concurrency``,
    ``--time-limit``, ``--test-count``, ssh opts)."""
    ap.add_argument("--nodes", default=None,
                    help="comma-separated node names")
    ap.add_argument("--nodes-file", default=None)
    ap.add_argument("--username", default="root")
    ap.add_argument("--password", default=None)
    ap.add_argument("--ssh-private-key", default=None)
    ap.add_argument("--concurrency", type=int, default=5)
    ap.add_argument("--time-limit", type=float, default=10.0)
    ap.add_argument("--test-count", type=int, default=1)
    ap.add_argument("--store-root", default="store")
    ap.add_argument("--seed", type=int, default=None)


def _nodes_from(args) -> Optional[list]:
    if args.nodes:
        return args.nodes.split(",")
    if args.nodes_file:
        with open(args.nodes_file) as f:
            return [ln.strip() for ln in f if ln.strip()]
    return None


def _suite_mode(mode: str, cluster_cls) -> str:
    """Translate the CLI's linearizable/sloppy vocabulary positionally
    through a fake-system class's ``MODES`` (first = safe, second =
    deliberately buggy) — e.g. sloppy → FakeBroker's "lossy"."""
    from jepsen_tpu.fake import FakeCluster
    base = FakeCluster.MODES
    return cluster_cls.MODES[base.index(mode)] if mode in base else mode


def _cmd_run(args) -> int:
    from jepsen_tpu import core
    from jepsen_tpu.fake import FakeBroker
    from jepsen_tpu.suites import (counter as counter_suite, etcd, mutex,
                                   queue, redis, register, set_suite)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s")
    nodes = _nodes_from(args)                 # the fake cluster must be
    builders: Dict[str, Callable[..., Dict[str, Any]]] = {  # built over them
        "register": lambda: register.register_test(
            mode=args.mode, time_limit=args.time_limit,
            concurrency=args.concurrency, seed=args.seed,
            with_nemesis=not args.no_nemesis, store=True,
            algorithm=args.algorithm, nodes=nodes or 5),
        "register-independent": lambda: register.independent_test(
            mode=args.mode, concurrency=args.concurrency,
            seed=args.seed, store=True),
        "mutex": lambda: mutex.mutex_test(
            mode=args.mode, time_limit=args.time_limit,
            concurrency=args.concurrency, seed=args.seed,
            with_nemesis=not args.no_nemesis, store=True,
            algorithm=args.algorithm, nodes=nodes or 5),
        "queue": lambda: queue.queue_test(
            mode=_suite_mode(args.mode, FakeBroker),
            time_limit=args.time_limit, concurrency=args.concurrency,
            seed=args.seed, with_nemesis=not args.no_nemesis, store=True,
            nodes=nodes or 5),
        "set": lambda: set_suite.set_test(
            mode=args.mode, time_limit=args.time_limit,
            concurrency=args.concurrency, seed=args.seed,
            with_nemesis=not args.no_nemesis, store=True, nodes=nodes or 5),
        "counter": lambda: counter_suite.counter_test(
            mode=args.mode, time_limit=args.time_limit,
            concurrency=args.concurrency, seed=args.seed,
            with_nemesis=not args.no_nemesis, store=True, nodes=nodes or 5),
        "etcd": lambda: etcd.etcd_test(
            mode=args.mode, time_limit=args.time_limit,
            concurrency=args.concurrency, seed=args.seed,
            with_nemesis=not args.no_nemesis, store=True,
            algorithm=args.algorithm, nodes=nodes or 5),
        "redis": lambda: redis.redis_test(
            mode=args.mode, time_limit=args.time_limit,
            concurrency=args.concurrency, seed=args.seed,
            with_nemesis=not args.no_nemesis, store=True,
            algorithm=args.algorithm, nodes=nodes or 5),
    }
    if args.suite not in builders:
        print(f"unknown suite {args.suite!r}; have {sorted(builders)}",
              file=sys.stderr)
        return 2
    ok = True
    for i in range(args.test_count):
        test = builders[args.suite]()
        test["store-root"] = args.store_root
        test["ssh"] = {"username": args.username,
                       "password": args.password,
                       "private-key-path": args.ssh_private_key}
        if getattr(args, "online", False):
            test["online-check"] = True
        done = core.run(test)
        valid = done["results"].get("valid")
        print(json.dumps({"run": i, "name": done["name"], "valid": valid,
                          "dir": done.get("dir"),
                          "ops": len(done["history"])}))
        ok = ok and valid is True
    return 0 if ok else 1


def _cmd_serve(args) -> int:
    """``serve`` — the RESULTS BROWSER: a read-only HTTP view over the
    store directory (runs, artifacts, verdict badges, the ``/engine``
    live-daemon stats page). It never checks anything. The checking
    daemon — device-resident engines serving ``POST /check`` traffic —
    is the separate ``check-serve`` subcommand."""
    from jepsen_tpu import web
    web.serve(root=args.store_root, port=args.port)
    return 0


def _cmd_check_serve(args) -> int:
    """``check-serve`` — the CHECKER-AS-A-SERVICE daemon (ISSUE 6):
    long-lived process holding compiled kernel geometries, union
    transition tensors, and the memo/compile caches hot, serving
    concurrent linearizability checks over HTTP with continuous
    multi-tenant batching. See docs/SERVING.md for the protocol."""
    import signal

    from jepsen_tpu import serve

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s")
    if args.coordinator:
        # pod mode: bring up jax.distributed BEFORE any backend spins
        # up. Rank 0 becomes the daemon (ONE fleet replica fronting
        # the whole pod); every other rank stays resident as a
        # compute peer and never binds a port or claims a lease.
        from jepsen_tpu.parallel import distributed
        distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes or None,
            process_id=args.process_id
            if args.process_id is not None else None)
        rank, n_ranks = distributed.process_info()
        if n_ranks > 1 and rank > 0:
            import signal as _sig

            def _peer_term(signum, frame):
                raise SystemExit(0)

            _sig.signal(_sig.SIGTERM, _peer_term)
            from jepsen_tpu.serve import http as serve_http
            serve_http.run_compute_peer(rank=rank, n_ranks=n_ranks)
            print(json.dumps({"shutdown": "clean", "peer": rank}))
            return 0
    engine_kw = {}
    if args.max_states:
        engine_kw["max_states"] = args.max_states
    daemon = serve.Daemon(
        port=args.port,
        host=args.host,
        queue_depth=args.queue_depth,
        max_inflight_per_tenant=args.tenant_inflight,
        group=args.group,
        engine_kw=engine_kw,
        store_root=args.store_root,
        persist=not args.no_persist_runs,
        journal=not args.no_journal,
        breaker=serve.CircuitBreaker(
            threshold=args.breaker_threshold,
            cooldown_s=args.breaker_cooldown),
        dispatch_deadline_s=args.dispatch_deadline or None,
        session_tenant_cap=args.session_tenant_cap,
        session_idle_ttl_s=args.session_idle_ttl or None,
        lanes=args.lanes,
        replica_id=args.replica_id or None,
        lease_ttl_s=args.lease_ttl)

    def _term(signum, frame):
        # SIGTERM == the orchestrator's polite stop: drain, then exit
        # cleanly (the CI serve-smoke job asserts this path)
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _term)
    print(f"jepsen-tpu check daemon: http://localhost:{daemon.port}/ "
          f"(POST /check, GET /check/<id>, GET /stats, GET /metrics, "
          f"POST /profile; store root {args.store_root})")
    daemon.serve_forever()
    print(json.dumps({"shutdown": "clean", **daemon.stats()},
                     default=str))
    return 0


def _load_history(path: str):
    import os

    from jepsen_tpu import history as h

    if os.path.isdir(path):
        from jepsen_tpu import store
        return store.load_history(path)
    if path.endswith(".edn"):
        return h.load_edn(path)
    return h.load_jsonl(path)


def _cmd_recheck(args) -> int:
    """Re-analyze stored histories offline — the TPU solver's entry point
    for existing Jepsen runs (reads our store dirs, bare history.jsonl
    paths, or upstream EDN histories). Several paths at once go through
    the lockstep batch engine (``reach.check_batch``): all histories
    advance together in one device walk — the batch axis is where the
    accelerator earns its keep (BASELINE.md round-4 batch rung)."""
    from jepsen_tpu import history as h
    from jepsen_tpu import models

    model = getattr(models, args.model.replace("-", "_"))()
    if len(args.path) > 1:
        from jepsen_tpu.checkers import facade, reach

        if args.algorithm != "auto":
            logging.getLogger("jepsen.cli").warning(
                "--algorithm %s is ignored with multiple paths: the "
                "lockstep batch engine checks them together",
                args.algorithm)
        # containment mirrors the single-path route's check_safe: an
        # unreadable path or a history the batch engines reject yields
        # its own {"valid": "unknown", "error": ...} line instead of a
        # traceback that swallows the good runs' verdicts
        loaded: list = []               # (path, history|None, error|None)
        for p in args.path:
            try:
                loaded.append((p, _load_history(p), None))
            except Exception as e:                      # noqa: BLE001
                loaded.append((p, None, f"{type(e).__name__}: {e}"))
        live = [(i, hist) for i, (_p, hist, err) in enumerate(loaded)
                if err is None]
        try:
            batch = reach.check_batch(model,
                                      [h.pack(hist) for _, hist in live])
            res_by_idx = {i: r for (i, _), r in zip(live, batch)}
        except Exception as e:                          # noqa: BLE001
            # batch path rejected (overflow, unhashable values, ...):
            # per-history auto chain with full error containment
            logging.getLogger("jepsen.cli").warning(
                "batch recheck failed (%r); per-history fallback", e)
            res_by_idx = {
                i: facade.check_safe(facade.linearizable(model),
                                     {"model": model}, hist)
                for i, hist in live}
        ok = True
        for i, (p, _hist, err) in enumerate(loaded):
            res = (res_by_idx[i] if err is None
                   else {"valid": "unknown", "error": err})
            ok = ok and res.get("valid") is True
            print(json.dumps({"path": p, **res}, default=str))
        return 0 if ok else 1
    from jepsen_tpu.checkers import facade

    history = _load_history(args.path[0])
    checker = facade.linearizable(model, algorithm=args.algorithm)
    res = facade.check_safe(checker, {"model": model}, history)
    print(json.dumps(res, indent=2, default=str))
    return 0 if res.get("valid") is True else 1


def _cmd_check(args) -> int:
    """``check`` — one-shot offline check of a history file. A
    transactional history (EDN/JSONL list-append ops, ``f == "txn"``
    — the Elle workload shape) routes through
    ``facade.auto_check_txn``; ``--txn`` forces that route, otherwise
    it is auto-detected from the ops. Non-txn histories take the
    ``recheck`` linearizable path against ``--model``. With
    ``--store-root`` the run persists as a browsable store dir — the
    anomaly report (classes + witness cycle) lands in results.json
    exactly like linear runs, and ``web.py`` renders the badges."""
    from jepsen_tpu import models
    from jepsen_tpu.checkers import facade

    history = _load_history(args.path)
    client_ops = [op for op in history if op.process != "nemesis"]
    is_txn = args.txn or (bool(client_ops)
                          and all(op.f == "txn" for op in client_ops))
    if is_txn:
        res = facade.auto_check_txn(history, {})
    else:
        model = getattr(models, args.model.replace("-", "_"))()
        checker = facade.linearizable(model, algorithm=args.algorithm)
        res = facade.check_safe(checker, {"model": model}, history)
    if args.store_root:
        import uuid

        from jepsen_tpu import store
        run_id = uuid.uuid4().hex[:8]
        name = "txn-check" if is_txn else f"check-{args.model}"
        res = dict(res)
        res["run-dir"] = store.save_check(args.store_root, name, run_id,
                                          list(history), res)
    print(json.dumps(res, indent=2, default=str))
    return 0 if res.get("valid") is True else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="jepsen-tpu",
        description="TPU-native distributed-systems safety testing")
    sub = ap.add_subparsers(dest="cmd", required=True)

    runp = sub.add_parser("run", help="run a test suite")
    _add_common(runp)
    runp.add_argument("--suite", default="register")
    runp.add_argument("--mode", default="linearizable",
                      choices=["linearizable", "sloppy"])
    runp.add_argument("--algorithm", default="auto")
    runp.add_argument("--no-nemesis", action="store_true")
    runp.add_argument("--online", action="store_true",
                      help="live linearizability monitoring: re-check the "
                           "history during the run and abort on the first "
                           "violation")
    runp.set_defaults(fn=_cmd_run)

    servep = sub.add_parser(
        "serve", help="browse stored results over HTTP (read-only; "
                      "the checking daemon is 'check-serve')")
    servep.add_argument("--store-root", default="store")
    servep.add_argument("--port", type=int, default=8080)
    servep.set_defaults(fn=_cmd_serve)

    csp = sub.add_parser(
        "check-serve",
        help="run the checker-as-a-service daemon: device-resident "
             "engines serving POST /check with continuous "
             "multi-tenant batching")
    csp.add_argument("--port", type=int, default=8642)
    csp.add_argument("--host", default="127.0.0.1",
                     help="bind address — loopback by default: this "
                          "endpoint ACCEPTS WORK (unauthenticated "
                          "compute + store writes), unlike the "
                          "read-only results browser; set 0.0.0.0 "
                          "deliberately to expose it")
    csp.add_argument("--store-root", default="store",
                     help="persistence root: completed checks land as "
                          "browsable runs, daemon stats under "
                          "<root>/serve/stats.json")
    csp.add_argument("--queue-depth", type=int, default=256,
                     help="admission bound; past it POST /check "
                          "returns 429")
    csp.add_argument("--tenant-inflight", type=int, default=8,
                     help="max in-flight requests per tenant "
                          "(fairness cap)")
    csp.add_argument("--group", type=int, default=32,
                     help="max lanes per coalesced dispatch group")
    csp.add_argument("--max-states", type=int, default=0,
                     help="engine max_states override (0 = default)")
    csp.add_argument("--no-persist-runs", action="store_true",
                     help="do not write completed checks into the "
                          "store")
    csp.add_argument("--no-journal", action="store_true",
                     help="disable the durable admission journal "
                          "(admitted requests then do NOT survive a "
                          "daemon crash)")
    csp.add_argument("--breaker-threshold", type=int, default=5,
                     help="consecutive device-path failures that "
                          "open the circuit breaker (degraded "
                          "host-side serving)")
    csp.add_argument("--breaker-cooldown", type=float, default=15.0,
                     help="seconds an open breaker waits before its "
                          "half-open device probe")
    csp.add_argument("--dispatch-deadline", type=float, default=0.0,
                     help="wall-clock cap per dispatch; a hung "
                          "dispatch past it is aborted and its "
                          "survivors requeued (0 = no cap)")
    csp.add_argument("--session-tenant-cap", type=int, default=64,
                     help="max OPEN streaming sessions per tenant "
                          "(429 cause tenant-cap past it; 0 = "
                          "unlimited)")
    csp.add_argument("--session-idle-ttl", type=float, default=3600.0,
                     help="force-close open sessions idle this many "
                          "seconds (exact close verdict + journal "
                          "marker; 0 = never)")
    csp.add_argument("--lanes", type=int, default=1,
                     help="dispatcher lanes (one dispatch thread + "
                          "circuit breaker each); match the device "
                          "count to keep every accelerator busy")
    csp.add_argument("--replica-id", default="",
                     help="fleet mode: unique name of this replica; "
                          "N daemons with distinct ids over one "
                          "--store-root partition the journal by "
                          "per-entry lease (empty = single daemon)")
    csp.add_argument("--lease-ttl", type=float, default=10.0,
                     help="fleet lease time-to-live in seconds; a "
                          "dead replica's work drains to survivors "
                          "after this long")
    csp.add_argument("--coordinator", default="",
                     help="pod mode: jax.distributed coordinator "
                          "address (host:port); rank 0 serves HTTP "
                          "and holds the fleet lease, other ranks "
                          "stay resident as compute peers")
    csp.add_argument("--num-processes", type=int, default=0,
                     help="pod mode: total process count (0 = "
                          "environment-discovered)")
    csp.add_argument("--process-id", type=int, default=None,
                     help="pod mode: this process's rank (unset = "
                          "environment-discovered)")
    csp.set_defaults(fn=_cmd_check_serve)

    ckp = sub.add_parser(
        "check",
        help="check one history file; txn (list-append) histories "
             "auto-route through the transactional checker")
    ckp.add_argument("path",
                     help="run dir, history.jsonl, or history.edn "
                          "(EDN list-append format supported)")
    ckp.add_argument("--txn", action="store_true",
                     help="force the transactional route (default: "
                          "auto-detected when every client op is a "
                          "txn)")
    ckp.add_argument("--model", default="cas-register",
                     help="model for NON-txn histories")
    ckp.add_argument("--algorithm", default="auto")
    ckp.add_argument("--store-root", default=None,
                     help="persist the check as a browsable store "
                          "run (anomaly report included)")
    ckp.set_defaults(fn=_cmd_check)

    rp = sub.add_parser("recheck",
                        help="re-analyze stored histories offline "
                             "(several paths = one lockstep batch)")
    rp.add_argument("path", nargs="+",
                    help="run dir(s), history.jsonl, or history.edn; "
                         "more than one path checks them all in one "
                         "lockstep batch on the device")
    rp.add_argument("--model", default="cas-register")
    rp.add_argument("--algorithm", default="auto",
                    help="engine for single-path rechecks (several "
                         "paths always use the batch engine)")
    rp.set_defaults(fn=_cmd_recheck)

    args = ap.parse_args(argv)
    from jepsen_tpu import envcheck
    envcheck.check_once()           # typo'd opt-outs warn, not no-op
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
