"""A fake replicated KV cluster with injectable faults.

No direct upstream analogue — the upstream exercises its full stack
against a docker-compose cluster of real sshd/DB containers (SURVEY.md
§4); this module plays that role in-process so the E2E path (generator →
client → nemesis → checker → store) runs anywhere, instantly.

Consistency modes:

- ``"linearizable"`` — one authoritative copy guarded by a lock; an op
  succeeds only if its coordinator can reach a majority of nodes.
  Histories are always linearizable (the checkers must agree).
- ``"sloppy"`` — per-node replicas; writes apply locally and replicate
  only to currently-reachable nodes; reads serve the local replica. Under
  a partition this yields stale reads and lost updates — real
  linearizability violations the checkers must catch. This is the
  "deliberately-buggy replicated register" of SURVEY.md §7.6.

Fault API (driven by :class:`jepsen_tpu.net.FakeNet` and the nemeses):
``drop_link / heal / set_latency / set_loss / kill_node / start_node /
pause_node / resume_node / bump_clock``.
"""
from __future__ import annotations

import random
import threading
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.util import majority


class Unavailable(Exception):
    """Definite failure: the op did not and will not take effect."""


class FakeTimeout(Exception):
    """Indeterminate failure: the op may or may not have taken effect."""


class _Node:
    def __init__(self, name: str):
        self.name = name
        self.alive = True
        self.data: Dict[Any, Any] = {}           # local replica (sloppy mode)
        self.clock_skew: float = 0.0
        self.pause = threading.Event()           # set = paused
        self.lock = threading.Lock()


class FakeCluster:
    #: subclasses with different consistency vocabularies override this
    #: (first entry = the safe mode, second = the deliberately buggy one)
    MODES = ("linearizable", "sloppy")

    def __init__(self, nodes: Sequence[str] = ("n1", "n2", "n3", "n4", "n5"),
                 mode: str = "linearizable", seed: Optional[int] = None,
                 base_latency: float = 0.0):
        assert mode in self.MODES
        self.mode = mode
        #: subclass-proof branch selector: MODES[0] is always the safe mode
        self.safe = mode == self.MODES[0]
        self.node_names: List[str] = list(nodes)
        self.nodes: Dict[str, _Node] = {n: _Node(n) for n in nodes}
        self.dropped: Set[Tuple[str, str]] = set()     # (src, dst)
        self.latency = base_latency
        self.loss = 0.0
        self._rng = random.Random(seed)
        self._global: Dict[Any, Any] = {}              # authoritative copy
        self._glock = threading.Lock()

    # -- fault API (nemesis-facing) ------------------------------------------
    def drop_link(self, src: str, dst: str) -> None:
        self.dropped.add((src, dst))

    def heal(self) -> None:
        self.dropped.clear()

    def set_latency(self, seconds: float) -> None:
        self.latency = seconds

    def set_loss(self, prob: float) -> None:
        self.loss = prob

    def kill_node(self, node: str) -> None:
        self.nodes[node].alive = False

    def start_node(self, node: str) -> None:
        n = self.nodes[node]
        n.alive = True
        if not self.safe:
            # a restarted node rejoins empty and catches up from whoever it
            # can reach (deliberately naive — data loss is a feature here)
            for peer in self._reachable_from(node):
                if peer != node and self.nodes[peer].alive:
                    n.data = dict(self.nodes[peer].data)
                    break

    def pause_node(self, node: str) -> None:
        self.nodes[node].pause.set()

    def resume_node(self, node: str) -> None:
        self.nodes[node].pause.clear()

    def bump_clock(self, node: str, skew: Optional[float]) -> None:
        self.nodes[node].clock_skew = skew or 0.0

    # -- connectivity --------------------------------------------------------
    def _link_ok(self, src: str, dst: str) -> bool:
        return (src, dst) not in self.dropped

    def _reachable_from(self, src: str) -> List[str]:
        """Nodes that can hear from ``src`` (and answer back)."""
        return [d for d in self.node_names
                if self.nodes[d].alive and self._link_ok(src, d)
                and self._link_ok(d, src)]

    def _has_majority(self, coord: str) -> bool:
        return len(self._reachable_from(coord)) >= majority(
            len(self.node_names))

    # -- client RPC ----------------------------------------------------------
    def _enter(self, node: str) -> _Node:
        n = self.nodes.get(node)
        if n is None:
            raise Unavailable(f"no such node {node}")
        if not n.alive:
            raise Unavailable(f"node {node} is down")   # connection refused
        if self.latency:
            _time.sleep(self.latency)
        if self.loss and self._rng.random() < self.loss:
            raise FakeTimeout(f"packet loss to {node}")
        if n.pause.is_set():
            # a SIGSTOPped server accepts the connection but never answers:
            # wait for resume up to a small bound, then time out
            # (indeterminate — the op may still execute on resume)
            deadline = _time.monotonic() + 0.5
            while n.pause.is_set():
                if _time.monotonic() > deadline:
                    raise FakeTimeout(f"node {node} unresponsive")
                _time.sleep(0.005)
        return n

    def read(self, node: str, key: Any) -> Any:
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._glock:
                return self._global.get(key)
        with n.lock:
            return n.data.get(key)

    def write(self, node: str, key: Any, value: Any) -> None:
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._glock:
                if not self._has_majority(node):       # re-check inside
                    raise FakeTimeout(f"{node} lost quorum mid-write")
                self._global[key] = value
            return
        self._sloppy_apply(n, key, lambda _: value)

    def cas(self, node: str, key: Any, old: Any, new: Any) -> bool:
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._glock:
                if self._global.get(key) != old:
                    return False
                self._global[key] = new
                return True
        with n.lock:
            if n.data.get(key) != old:
                return False
        self._sloppy_apply(n, key, lambda _: new)
        return True

    def sadd(self, node: str, key: Any, value: Any) -> None:
        """Add ``value`` to the set at ``key`` (grow-only-set workload)."""
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._glock:
                self._global.setdefault(key, set()).add(value)
            return
        # the sloppy bug: the add replicates only to currently-reachable
        # peers, and replicas never merge — partitioned adds are lost to
        # any single node's final read
        self._sloppy_apply(n, key, lambda cur: (set(cur or ()) | {value}))

    def sread(self, node: str, key: Any) -> list:
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._glock:
                return sorted(self._global.get(key) or (), key=repr)
        with n.lock:
            return sorted(n.data.get(key) or (), key=repr)

    def txn(self, node: str, micro_ops: Sequence[Sequence[Any]]) -> list:
        """Execute a list-append transaction — ``[["append", k, v],
        ["r", k, None], ...]`` — returning the completed micro-ops
        (reads filled with the observed list). Safe mode commits the
        WHOLE transaction atomically under the global lock (so
        histories are serializable by construction); sloppy mode
        applies each micro-op to the local replica and replicates
        last-writer-wins — concurrent/partitioned appends clobber
        whole lists, surfacing as genuine Elle anomalies
        (incompatible orders, lost appends) the txn checker must
        catch."""
        n = self._enter(node)
        out = []
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._glock:
                if not self._has_majority(node):       # re-check inside
                    raise FakeTimeout(f"{node} lost quorum mid-txn")
                for kind, key, v in micro_ops:
                    if kind == "append":
                        self._global.setdefault(("txn", key),
                                                []).append(v)
                        out.append(["append", key, v])
                    else:
                        out.append(["r", key, list(
                            self._global.get(("txn", key)) or ())])
            return out
        for kind, key, v in micro_ops:
            if kind == "append":
                self._sloppy_apply(n, ("txn", key),
                                   lambda cur, v=v: list(cur or ()) + [v])
                out.append(["append", key, v])
            else:
                with n.lock:
                    out.append(["r", key,
                                list(n.data.get(("txn", key)) or ())])
        return out

    def incr(self, node: str, key: Any, delta: Any) -> None:
        """Increment the counter at ``key`` by ``delta``."""
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._glock:
                self._global[key] = (self._global.get(key) or 0) + delta
            return
        # the sloppy bug: the post-increment VALUE is replicated (last
        # writer wins), so concurrent/partitioned increments clobber each
        # other — reads drift below the definite sum
        self._sloppy_apply(n, key, lambda cur: (cur or 0) + delta)

    def _sloppy_apply(self, n: _Node, key: Any, f) -> None:
        """Apply locally, then best-effort replicate to reachable peers —
        the bug: unreachable peers keep stale data and keep serving it."""
        with n.lock:
            n.data[key] = f(n.data.get(key))
            value = n.data[key]
        for peer in self._reachable_from(n.name):
            p = self.nodes[peer]
            if p is n or p.pause.is_set():
                continue
            with p.lock:
                p.data[key] = value
