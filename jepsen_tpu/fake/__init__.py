"""In-process fake distributed systems for E2E testing without SSH or
docker (SURVEY.md §4 "implication for the rebuild" #4): a deliberately
configurable replicated KV store with injectable partitions, pauses,
kills, latency, loss, and clock skew.
"""
from jepsen_tpu.fake.broker import FakeBroker
from jepsen_tpu.fake.cluster import FakeCluster, Unavailable

__all__ = ["FakeBroker", "FakeCluster", "Unavailable"]
