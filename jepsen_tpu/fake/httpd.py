"""HTTP front-end for the fake cluster: one etcd-v2-dialect server per
node, so suites can exercise a REAL wire protocol (sockets, timeouts,
HTTP error mapping) end-to-end without an external binary.

Upstream's flagship ``etcd/`` suite (SURVEY.md §2.5) talks etcd's v2
REST API (``GET/PUT /v2/keys/<key>``, CAS via ``prevValue``); this
module serves the same dialect backed by a
:class:`~jepsen_tpu.fake.cluster.FakeCluster` node, so nemesis
partitions/pauses surface as real 503s and socket timeouts. The
:class:`~jepsen_tpu.suites.etcd.EtcdHttpClient` pointed at real etcd v2
endpoints speaks the identical protocol.

Error mapping (etcd-compatible where it matters):

- key missing            → 404 (errorCode 100)
- CAS precondition fails → 412 (errorCode 101) — a clean :fail
- node partitioned/down  → 503 — definite :fail (no effect)
- backend timeout        → server sleeps past the client's socket
  timeout → the client sees a timeout → indeterminate :info
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs, unquote, urlparse

from jepsen_tpu.fake import Unavailable
from jepsen_tpu.fake.cluster import FakeCluster, FakeTimeout

_PREFIX = "/v2/keys/"


class _Handler(BaseHTTPRequestHandler):
    # cluster / node / timeout_hold_s live on the ThreadingHTTPServer
    # instance (stamped by HttpKVFrontend.start), accessed via self.server
    server_version = "jepsen-tpu-fake-etcd/1"

    def log_message(self, fmt, *args):   # silence per-request stderr spam
        pass

    def _send(self, code: int, body: Dict[str, Any]) -> None:
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _key(self) -> Optional[str]:
        path = urlparse(self.path).path
        if not path.startswith(_PREFIX):
            return None
        return unquote(path[len(_PREFIX):])

    def _guard(self, fn):
        """Run a cluster op with etcd-ish error mapping."""
        srv = self.server
        try:
            return True, fn()
        except Unavailable as e:
            self._send(503, {"errorCode": 300, "message": str(e)})
        except FakeTimeout:
            # hold the socket past the client's timeout so it observes a
            # real indeterminate network timeout, then answer 504 for
            # stragglers with longer timeouts
            time.sleep(getattr(srv, "timeout_hold_s", 2.0))
            try:
                self._send(504, {"errorCode": 301, "message": "timeout"})
            except OSError:
                pass        # the client already hung up — that's the point
        return False, None

    def do_GET(self):                                   # noqa: N802
        key = self._key()
        if key is None:
            return self._send(404, {"errorCode": 100, "message": "bad path"})
        srv = self.server
        okflag, value = self._guard(
            lambda: srv.cluster.read(srv.node, key))
        if not okflag:
            return
        if value is None:
            return self._send(404, {"errorCode": 100,
                                    "message": "Key not found", "key": key})
        self._send(200, {"action": "get",
                         "node": {"key": key, "value": str(value)}})

    def do_PUT(self):                                   # noqa: N802
        key = self._key()
        if key is None:
            return self._send(404, {"errorCode": 100, "message": "bad path"})
        length = int(self.headers.get("Content-Length") or 0)
        form = parse_qs(self.rfile.read(length).decode())
        if "value" not in form:
            return self._send(400, {"errorCode": 209,
                                    "message": "value required"})
        value = form["value"][0]
        srv = self.server
        if "prevValue" in form:                         # compare-and-swap
            prev = form["prevValue"][0]
            # real etcd v2 distinguishes a missing key (404, errorCode
            # 100) from a compare failure (412, errorCode 101); both are
            # definite no-effect outcomes, so the pre-read race below
            # only ever picks between two linearizable error replies
            okflag, cur = self._guard(
                lambda: srv.cluster.read(srv.node, key))
            if not okflag:
                return
            if cur is None:
                return self._send(404, {"errorCode": 100,
                                        "message": "Key not found",
                                        "key": key})

            def _cas():
                return srv.cluster.cas(srv.node, key, prev, value)

            okflag, swapped = self._guard(_cas)
            if not okflag:
                return
            if not swapped:
                return self._send(412, {"errorCode": 101,
                                        "message": "Compare failed"})
            return self._send(200, {"action": "compareAndSwap",
                                    "node": {"key": key, "value": value}})
        okflag, _ = self._guard(
            lambda: srv.cluster.write(srv.node, key, value))
        if not okflag:
            return
        self._send(200, {"action": "set",
                         "node": {"key": key, "value": value}})


class HttpKVFrontend:
    """One HTTP server per cluster node, on loopback ephemeral ports.
    ``endpoints`` maps node name → base URL."""

    def __init__(self, cluster: FakeCluster,
                 timeout_hold_s: float = 2.0):
        self.cluster = cluster
        self.timeout_hold_s = timeout_hold_s
        self._servers: List[ThreadingHTTPServer] = []
        self._threads: List[threading.Thread] = []
        self.endpoints: Dict[str, str] = {}

    def start(self) -> "HttpKVFrontend":
        for node in self.cluster.nodes:
            srv = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
            srv.cluster = self.cluster                  # type: ignore
            srv.node = node                             # type: ignore
            srv.timeout_hold_s = self.timeout_hold_s    # type: ignore
            t = threading.Thread(target=srv.serve_forever, daemon=True,
                                 name=f"fake-etcd-{node}")
            t.start()
            self._servers.append(srv)
            self._threads.append(t)
            self.endpoints[node] = \
                f"http://127.0.0.1:{srv.server_address[1]}"
        return self

    def stop(self) -> None:
        for srv in self._servers:
            srv.shutdown()
            srv.server_close()
        for t in self._threads:
            t.join(5)
        self._servers, self._threads = [], []
