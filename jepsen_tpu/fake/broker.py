"""A fake replicated message broker with injectable faults.

Plays the role of the upstream queue-suite targets (``rabbitmq/``,
``kafka/`` — SURVEY.md §2.5) the way :mod:`.cluster` plays etcd: an
in-process system-under-test so the queue workload, nemesis, and the
``queue`` / ``total-queue`` checkers exercise end-to-end without SSH.

Reuses :class:`~jepsen_tpu.fake.cluster.FakeCluster`'s node/link/fault
plumbing (and its exception types); the datum is a queue instead of a KV
map. Consistency modes:

- ``"safe"`` — one authoritative durable queue guarded by a lock; an op
  succeeds only if its coordinator can reach a majority of nodes.
  Every acknowledged enqueue is dequeued exactly once by a full drain.
- ``"lossy"`` — per-node replica queues with best-effort replication,
  and a RabbitMQ-autoheal-style reconciliation on :meth:`heal`: one
  partition side wins wholesale and the other side's divergent state is
  discarded. Messages acknowledged only on the losing side are LOST
  (caught by ``total-queue``); messages the losing side had consumed
  are resurrected and dequeued again (caught by ``queue`` as overdrawn).
"""
from __future__ import annotations

from collections import deque
from typing import Any, Deque, Optional, Sequence

from jepsen_tpu.fake.cluster import FakeCluster, FakeTimeout, Unavailable

__all__ = ["FakeBroker", "Empty", "FakeTimeout", "Unavailable"]


class Empty(Exception):
    """Dequeue found no message (a definite, clean ``fail``)."""


class FakeBroker(FakeCluster):
    MODES = ("safe", "lossy")

    def __init__(self, nodes: Sequence[str] = ("n1", "n2", "n3", "n4", "n5"),
                 mode: str = "safe", seed: Optional[int] = None,
                 base_latency: float = 0.0):
        super().__init__(nodes, mode=mode, seed=seed,
                         base_latency=base_latency)
        self._queue: Deque[Any] = deque()        # authoritative (safe mode)
        for n in self.nodes.values():
            n.queue = deque()                    # local replica (lossy mode)

    # -- fault API overrides -------------------------------------------------
    def heal(self) -> None:
        super().heal()
        if not self.safe:
            self._autoheal()

    def _autoheal(self) -> None:
        """RabbitMQ-autoheal analogue: the first alive node's replica wins
        and overwrites everyone else's — the deliberate bug."""
        winner = next((self.nodes[n] for n in self.node_names
                       if self.nodes[n].alive), None)
        if winner is None:
            return
        with winner.lock:
            snapshot = list(winner.queue)
        for name in self.node_names:
            n = self.nodes[name]
            if n is winner or not n.alive:
                continue
            with n.lock:
                n.queue = deque(snapshot)

    def start_node(self, node: str) -> None:
        n = self.nodes[node]
        n.alive = True
        if not self.safe:
            # a restarted broker node rejoins empty and copies whichever
            # peer it reaches first (data loss is a feature here)
            n.queue = deque()
            for peer in self._reachable_from(node):
                if peer != node and self.nodes[peer].alive:
                    with self.nodes[peer].lock:
                        n.queue = deque(self.nodes[peer].queue)
                    break

    # -- client RPC ----------------------------------------------------------
    def enqueue(self, node: str, value: Any) -> None:
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._glock:
                if not self._has_majority(node):
                    raise FakeTimeout(f"{node} lost quorum mid-enqueue")
                self._queue.append(value)
            return
        with n.lock:
            n.queue.append(value)
        for peer in self._reachable_from(n.name):
            p = self.nodes[peer]
            if p is n or p.pause.is_set():
                continue
            with p.lock:
                p.queue.append(value)

    def dequeue(self, node: str) -> Any:
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._glock:
                if not self._queue:
                    raise Empty("queue empty")
                return self._queue.popleft()
        with n.lock:
            if not n.queue:
                raise Empty(f"queue empty on {node}")
            value = n.queue.popleft()
        # best-effort delete on reachable peers; unreachable replicas keep
        # the message and will serve it again (the duplicate-delivery bug)
        for peer in self._reachable_from(n.name):
            p = self.nodes[peer]
            if p is n or p.pause.is_set():
                continue
            with p.lock:
                try:
                    p.queue.remove(value)
                except ValueError:
                    pass
        return value

    def empty(self) -> bool:
        """True when no replica anywhere still holds a message (drives the
        drain phase's stop condition)."""
        if self.safe:
            with self._glock:
                return not self._queue
        return all(not self.nodes[n].queue for n in self.node_names
                   if self.nodes[n].alive)
