"""RESP (Redis Serialization Protocol) front-end for the fake cluster:
one RESP2 TCP server per node, so suites can exercise a second REAL wire
protocol (binary-safe framing over raw sockets) end-to-end without an
external binary.

Upstream-era Jepsen drove Redis-family systems over this protocol
(SURVEY.md §2.5 lists the redis-style suites among the per-DB dirs); this
module serves the dialect backed by a
:class:`~jepsen_tpu.fake.cluster.FakeCluster` node, so nemesis
partitions/pauses surface as real ``-CLUSTERDOWN`` errors and socket
timeouts. The :class:`~jepsen_tpu.suites.redis.RespClient` pointed at a
real Redis speaks the identical protocol (CAS is sent as the canonical
``EVAL`` compare-and-set script a real server would execute atomically;
this fake recognizes that script's shape and applies the same
semantics).

Commands: ``PING``, ``GET k``, ``SET k v``,
``EVAL <cas-script> 1 k old new``.

Error mapping:

- key missing            → RESP nil bulk (``$-1``)
- CAS compare fails      → ``:0`` (script returns 0 — a clean :fail)
- node partitioned/down  → ``-CLUSTERDOWN`` — definite :fail (no effect)
- backend timeout        → server holds the socket past the client's
  timeout → the client sees a real network timeout → indeterminate :info
"""
from __future__ import annotations

import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

from jepsen_tpu.fake import Unavailable
from jepsen_tpu.fake.cluster import FakeCluster, FakeTimeout

# the canonical Redis compare-and-set script (what a real client EVALs);
# the fake matches on its first characters to recognize intent
CAS_SCRIPT = ("if redis.call('get', KEYS[1]) == ARGV[1] then "
              "return redis.call('set', KEYS[1], ARGV[2]) and 1 "
              "else return 0 end")


def _read_exact(rf, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = rf.read(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def read_command(rf) -> Optional[List[bytes]]:
    """Parse one RESP array-of-bulk-strings command; None on clean EOF."""
    line = rf.readline()
    if not line:
        return None
    if not line.startswith(b"*"):
        raise ValueError(f"expected array, got {line!r}")
    n = int(line[1:].rstrip())
    parts: List[bytes] = []
    for _ in range(n):
        hdr = rf.readline()
        if not hdr.startswith(b"$"):
            raise ValueError(f"expected bulk string, got {hdr!r}")
        ln = int(hdr[1:].rstrip())
        parts.append(_read_exact(rf, ln))
        _read_exact(rf, 2)                              # trailing CRLF
    return parts


def bulk(v: Optional[str]) -> bytes:
    if v is None:
        return b"$-1\r\n"
    data = str(v).encode()
    return b"$%d\r\n%s\r\n" % (len(data), data)


class _Handler(socketserver.StreamRequestHandler):
    # cluster / node / timeout_hold_s live on the server instance

    def handle(self):
        while True:
            try:
                cmd = read_command(self.rfile)
            except (ValueError, ConnectionError, OSError):
                return
            if cmd is None:
                return
            try:
                reply = self._dispatch(cmd)
            except Unavailable as e:
                reply = b"-CLUSTERDOWN %s\r\n" % str(e).encode()
            except FakeTimeout:
                # hold the socket past the client's timeout so it
                # observes a real indeterminate network timeout
                time.sleep(getattr(self.server, "timeout_hold_s", 2.0))
                reply = b"-ERR timeout\r\n"
            except Exception as e:                      # noqa: BLE001
                reply = b"-ERR %s\r\n" % type(e).__name__.encode()
            try:
                self.wfile.write(reply)
            except OSError:
                return              # client hung up mid-timeout: the point

    def _dispatch(self, cmd: List[bytes]) -> bytes:
        srv = self.server
        name = cmd[0].upper()
        if name == b"PING":
            return b"+PONG\r\n"
        if name == b"GET" and len(cmd) == 2:
            v = srv.cluster.read(srv.node, cmd[1].decode())
            return bulk(None if v is None else str(v))
        if name == b"SET" and len(cmd) >= 3:
            srv.cluster.write(srv.node, cmd[1].decode(), cmd[2].decode())
            return b"+OK\r\n"
        if name == b"EVAL" and len(cmd) >= 6 and \
                cmd[1].decode().replace(" ", "").startswith(
                    "ifredis.call('get',KEYS[1])==ARGV[1]"):
            key, old, new = (cmd[3].decode(), cmd[4].decode(),
                             cmd[5].decode())
            # one atomic cluster op; a missing key compares unequal to
            # any old value, exactly as the script's nil would
            swapped = srv.cluster.cas(srv.node, key, old, new)
            return b":1\r\n" if swapped else b":0\r\n"
        return b"-ERR unknown command\r\n"


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class RespKVFrontend:
    """One RESP server per cluster node, on loopback ephemeral ports.
    ``endpoints`` maps node name → ``(host, port)``."""

    def __init__(self, cluster: FakeCluster, timeout_hold_s: float = 2.0):
        self.cluster = cluster
        self.timeout_hold_s = timeout_hold_s
        self._servers: List[_Server] = []
        self._threads: List[threading.Thread] = []
        self.endpoints: Dict[str, Tuple[str, int]] = {}

    def start(self) -> "RespKVFrontend":
        for node in self.cluster.nodes:
            srv = _Server(("127.0.0.1", 0), _Handler)
            srv.cluster = self.cluster                  # type: ignore
            srv.node = node                             # type: ignore
            srv.timeout_hold_s = self.timeout_hold_s    # type: ignore
            t = threading.Thread(target=srv.serve_forever, daemon=True,
                                 name=f"fake-redis-{node}")
            t.start()
            self._servers.append(srv)
            self._threads.append(t)
            self.endpoints[node] = ("127.0.0.1", srv.server_address[1])
        return self

    def stop(self) -> None:
        for srv in self._servers:
            srv.shutdown()
            srv.server_close()
        for t in self._threads:
            t.join(5)
        self._servers, self._threads = [], []
