"""A fake distributed lock service — plays the role upstream's
``zookeeper/`` suite's real ZooKeeper ensemble plays (SURVEY.md §2.5: the
zookeeper lock workload checked against the ``mutex`` model).

Modes mirror :class:`~jepsen_tpu.fake.cluster.FakeCluster`:

- ``"linearizable"`` — one global lock; try-acquire requires the contacted
  node to reach a quorum. Histories always satisfy the mutex model.
- ``"sloppy"`` — each side of a partition keeps granting from its own
  local view: two holders at once — a mutex violation the checker must
  catch.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence

from jepsen_tpu.fake.cluster import FakeCluster, FakeTimeout, Unavailable


class FakeLockService(FakeCluster):
    """Reuses FakeCluster's node/link/fault plumbing; the datum is one
    lock (per name) instead of a KV map."""

    def __init__(self, nodes: Sequence[str] = ("n1", "n2", "n3", "n4", "n5"),
                 mode: str = "linearizable", seed: Optional[int] = None):
        super().__init__(nodes, mode=mode, seed=seed)
        self._lock_holder: Dict[Any, Any] = {}          # global (linearizable)
        self._llock = threading.Lock()
        for n in self.nodes.values():
            n.data = {}                                 # name -> holder

    # -- lock RPC ------------------------------------------------------------
    def acquire(self, node: str, name: Any, holder: Any) -> bool:
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._llock:
                if self._lock_holder.get(name) is not None:
                    return False
                self._lock_holder[name] = holder
                return True
        with n.lock:
            if n.data.get(name) is not None:
                return False
        self._replicate(n, name, holder)
        return True

    def release(self, node: str, name: Any, holder: Any) -> bool:
        n = self._enter(node)
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._llock:
                if self._lock_holder.get(name) != holder:
                    return False
                self._lock_holder[name] = None
                return True
        with n.lock:
            if n.data.get(name) != holder:
                return False
        self._replicate(n, name, None)
        return True

    def _replicate(self, n, name: Any, holder: Any) -> None:
        with n.lock:
            n.data[name] = holder
        for peer in self._reachable_from(n.name):
            p = self.nodes[peer]
            if p is n or p.pause.is_set():
                continue
            with p.lock:
                p.data[name] = holder
