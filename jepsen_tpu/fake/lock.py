"""A fake distributed lock service — plays the role upstream's
``zookeeper/`` suite's real ZooKeeper ensemble plays (SURVEY.md §2.5: the
zookeeper lock workload checked against the ``mutex`` model).

Modes mirror :class:`~jepsen_tpu.fake.cluster.FakeCluster`:

- ``"linearizable"`` — one global lock; try-acquire requires the contacted
  node to reach a quorum. Histories always satisfy the mutex model.
- ``"sloppy"`` — each side of a partition keeps granting from its own
  local view: two holders at once — a mutex violation the checker must
  catch.
- ``"leases"`` — the classic lease-based lock whose safety DEPENDS ON
  CLOCKS: a grant carries a deadline, and expiry is judged by the
  *contacted node's* local clock (``monotonic + clock_skew``). With
  synchronized clocks and a lease longer than the test this is safe;
  bump one node's clock past the TTL (``nemesis.clock_nemesis`` /
  ``bump-time``) and that node hands the lock to a second holder while
  the first still holds it — the canonical clock-skew mutex violation
  (upstream: the Jepsen analyses of lease locks + ``nemesis.time``;
  SURVEY.md §2.1 clock-fault row).
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Dict, Optional, Sequence, Tuple

from jepsen_tpu.fake.cluster import FakeCluster, FakeTimeout, Unavailable


class FakeLockService(FakeCluster):
    """Reuses FakeCluster's node/link/fault plumbing; the datum is one
    lock (per name) instead of a KV map."""

    MODES = ("linearizable", "sloppy", "leases")

    def __init__(self, nodes: Sequence[str] = ("n1", "n2", "n3", "n4", "n5"),
                 mode: str = "linearizable", seed: Optional[int] = None,
                 lease_ttl: float = 30.0):
        super().__init__(nodes, mode=mode, seed=seed)
        self._lock_holder: Dict[Any, Any] = {}          # global (linearizable)
        #: leases mode: name -> (holder, deadline on the granting
        #: node's clock). One global table — the fault modeled is clock
        #: skew, not replication lag.
        self._leases: Dict[Any, Tuple[Any, float]] = {}
        self.lease_ttl = lease_ttl
        self._llock = threading.Lock()
        for n in self.nodes.values():
            n.data = {}                                 # name -> holder

    def _node_now(self, node: str) -> float:
        """The contacted node's view of time — the lever clock faults
        pull (``bump_clock`` sets ``clock_skew``)."""
        return _time.monotonic() + self.nodes[node].clock_skew

    # -- lock RPC ------------------------------------------------------------
    def acquire(self, node: str, name: Any, holder: Any) -> bool:
        n = self._enter(node)
        if self.mode == "leases":
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            now = self._node_now(node)
            with self._llock:
                rec = self._leases.get(name)
                if rec is not None and now < rec[1]:
                    return False         # unexpired BY THIS NODE'S CLOCK
                self._leases[name] = (holder, now + self.lease_ttl)
                return True
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._llock:
                if self._lock_holder.get(name) is not None:
                    return False
                self._lock_holder[name] = holder
                return True
        with n.lock:
            if n.data.get(name) is not None:
                return False
        self._replicate(n, name, holder)
        return True

    def release(self, node: str, name: Any, holder: Any) -> bool:
        n = self._enter(node)
        if self.mode == "leases":
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._llock:
                rec = self._leases.get(name)
                if rec is None or rec[0] != holder:
                    return False
                del self._leases[name]
                return True
        if self.safe:
            if not self._has_majority(node):
                raise Unavailable(f"{node} lost quorum")
            with self._llock:
                if self._lock_holder.get(name) != holder:
                    return False
                self._lock_holder[name] = None
                return True
        with n.lock:
            if n.data.get(name) != holder:
                return False
        self._replicate(n, name, None)
        return True

    def _replicate(self, n, name: Any, holder: Any) -> None:
        with n.lock:
            n.data[name] = holder
        for peer in self._reachable_from(n.name):
            p = self.nodes[peer]
            if p is n or p.pause.is_set():
                continue
            with p.lock:
                p.data[name] = holder
