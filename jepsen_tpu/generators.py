"""Workload generators — upstream ``jepsen/src/jepsen/generator.clj``
(SURVEY.md §2.1, L3).

The upstream-era protocol is ``(op gen test process) -> op | nil``, called
concurrently by every worker thread; most combinators guard internal atoms.
Here a generator is any object with ``op(test, process) -> dict | None``
(``None`` = exhausted, the worker exits); stateful combinators synchronize
internally, so one generator instance may be shared by all workers exactly
as upstream.

Emitted ops are *partial* dicts — ``{"f": ..., "value": ...}`` — that the
runner completes with ``process``/``type``/``time``/``index``. A generator
may also emit ``{"sleep": seconds}`` (the worker naps, upstream
``gen/sleep``) or ``{"pending": True}`` (nothing *yet* — try again; used by
``stagger``-style pacing and ``phases`` hand-off).

Plain data is promoted automatically: a dict is a generator of itself
forever? — no: a dict is ``once``; a list/tuple is ``seq``; a callable
``() -> dict | None`` is wrapped. (Upstream promotes maps to endless
repeats in the *new* generator era; this code follows the classic era where
``gen/once`` wraps single maps, which is what the combinators below
expect.)
"""
from __future__ import annotations

import itertools
import logging
import random
import threading
import time as _time
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Sequence, Union

log = logging.getLogger("jepsen.generator")

OpSketch = Optional[Dict[str, Any]]
NEMESIS = "nemesis"


class Generator:
    """Base generator (upstream ``jepsen.generator/Generator`` protocol)."""

    def op(self, test: Mapping, process: Any) -> OpSketch:
        raise NotImplementedError


GenLike = Union[Generator, Dict[str, Any], Sequence, Callable[[], OpSketch], None]


def gen(g: GenLike) -> Generator:
    """Promote plain data to a generator (see module docstring)."""
    if g is None:
        return Void()
    if isinstance(g, Generator):
        return g
    if isinstance(g, dict):
        return Once(g)
    if callable(g):
        return Fn(g)
    if isinstance(g, (list, tuple)):
        return Seq(g)
    raise TypeError(f"cannot promote {type(g).__name__} to a generator")


class Void(Generator):
    """Immediately exhausted (upstream ``gen/void``)."""

    def op(self, test, process):
        return None


class Once(Generator):
    """Emit one op sketch to exactly one worker, then exhaust (upstream
    ``gen/once``)."""

    def __init__(self, sketch: Dict[str, Any]):
        self._sketch = sketch
        self._lock = threading.Lock()
        self._done = False

    def op(self, test, process):
        with self._lock:
            if self._done:
                return None
            self._done = True
            return dict(self._sketch)


class Repeat(Generator):
    """Emit the same sketch forever (or ``n`` times) (new-era map promotion
    / ``gen/repeat``)."""

    def __init__(self, sketch: Dict[str, Any], n: Optional[int] = None):
        self._sketch = sketch
        self._n = n
        self._lock = threading.Lock()

    def op(self, test, process):
        if self._n is None:
            return dict(self._sketch)
        with self._lock:
            if self._n <= 0:
                return None
            self._n -= 1
            return dict(self._sketch)


class Fn(Generator):
    """Each call invokes ``f`` (no args, or (test, process) if it accepts
    them) for a fresh sketch — the workhorse for random workloads."""

    def __init__(self, f: Callable):
        self._f = f
        try:
            import inspect
            self._arity = len(inspect.signature(f).parameters)
        except (TypeError, ValueError):
            self._arity = 0

    def op(self, test, process):
        return self._f(test, process) if self._arity >= 2 else self._f()


class Seq(Generator):
    """Drain an iterable of sketches/sub-generators, one element at a time;
    each element serves to exhaustion before the next (upstream
    ``gen/seq``). Thread-safe; the current element's ``op`` runs OUTSIDE
    the lock (it may block, e.g. a ``Synchronize`` barrier — holding the
    lock would deadlock the other workers the barrier waits for)."""

    def __init__(self, xs: Iterable):
        self._it = iter(xs)
        self._cur: Optional[Generator] = None
        self._done = False
        self._lock = threading.Lock()

    def op(self, test, process):
        while True:
            with self._lock:
                if self._done:
                    return None
                if self._cur is None:
                    try:
                        self._cur = gen(next(self._it))
                    except StopIteration:
                        self._done = True
                        return None
                cur = self._cur
            sketch = cur.op(test, process)
            if sketch is not None:
                return sketch
            with self._lock:
                if self._cur is cur:        # only the first observer advances
                    self._cur = None


def seq(*gens: GenLike) -> Seq:
    return Seq(gens)


def concat(*gens: GenLike) -> Seq:
    """Serve each generator to exhaustion, in order (upstream
    ``gen/concat``)."""
    return Seq(gens)


def cycle(g: GenLike, times: Optional[int] = None) -> Seq:
    """Serve ``g`` repeatedly (upstream ``gen/cycle``). A shared Generator
    instance stays exhausted, so pass plain data (re-promoted fresh each
    round) or a factory callable returning a fresh generator per round."""
    n = itertools.count() if times is None else range(times)
    if callable(g) and not isinstance(g, Generator):
        return Seq(g() for _ in n)
    return Seq(g for _ in n)


class Mix(Generator):
    """Uniform random choice among sub-generators per op; exhausted members
    drop out (upstream ``gen/mix``)."""

    def __init__(self, gens: Sequence[GenLike], seed: Optional[int] = None):
        self._gens: List[Generator] = [gen(g) for g in gens]
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def op(self, test, process):
        while True:
            with self._lock:
                if not self._gens:
                    return None
                g = self._rng.choice(self._gens)
            sketch = g.op(test, process)
            if sketch is not None:
                return sketch
            with self._lock:
                if g in self._gens:
                    self._gens.remove(g)


def mix(*gens: GenLike, seed: Optional[int] = None) -> Mix:
    return Mix(list(gens), seed=seed)


class Stagger(Generator):
    """Uniform-random delay (mean ``dt``) before each op, desynchronizing
    workers (upstream ``gen/stagger``)."""

    def __init__(self, dt: float, g: GenLike, seed: Optional[int] = None):
        self._dt = dt
        self._gen = gen(g)
        self._rng = random.Random(seed)

    def op(self, test, process):
        _time.sleep(self._rng.uniform(0, 2 * self._dt))
        return self._gen.op(test, process)


def stagger(dt: float, g: GenLike) -> Stagger:
    return Stagger(dt, g)


class Delay(Generator):
    """Fixed delay before every op (upstream ``gen/delay``)."""

    def __init__(self, dt: float, g: GenLike):
        self._dt = dt
        self._gen = gen(g)

    def op(self, test, process):
        _time.sleep(self._dt)
        return self._gen.op(test, process)


def delay(dt: float, g: GenLike) -> Delay:
    return Delay(dt, g)


class Sleep(Generator):
    """Emit a single ``{"sleep": dt}`` directive (upstream ``gen/sleep``)."""

    def __init__(self, dt: float):
        self._once = Once({"sleep": dt})

    def op(self, test, process):
        return self._once.op(test, process)


def sleep(dt: float) -> Sleep:
    return Sleep(dt)


class TimeLimit(Generator):
    """Exhaust ``dt`` seconds after the first op is requested (upstream
    ``gen/time-limit``)."""

    def __init__(self, dt: float, g: GenLike):
        self._dt = dt
        self._gen = gen(g)
        self._deadline: Optional[float] = None
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            if self._deadline is None:
                self._deadline = _time.monotonic() + self._dt
            expired = _time.monotonic() >= self._deadline
        if expired:
            return None
        return self._gen.op(test, process)


def time_limit(dt: float, g: GenLike) -> TimeLimit:
    return TimeLimit(dt, g)


class Limit(Generator):
    """At most ``n`` ops total (upstream ``gen/limit``)."""

    def __init__(self, n: int, g: GenLike):
        self._n = n
        self._gen = gen(g)
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            if self._n <= 0:
                return None
            self._n -= 1
        sketch = self._gen.op(test, process)
        if sketch is None:
            with self._lock:
                self._n = 0
        return sketch


def limit(n: int, g: GenLike) -> Limit:
    return Limit(n, g)


class On(Generator):
    """Route to ``g`` only for processes satisfying ``pred``; others see
    exhaustion (upstream ``gen/on`` / ``gen/filter`` over processes)."""

    def __init__(self, pred: Callable[[Any], bool], g: GenLike):
        self._pred = pred
        self._gen = gen(g)

    def op(self, test, process):
        if not self._pred(process):
            return None
        return self._gen.op(test, process)


def on(pred: Callable[[Any], bool], g: GenLike) -> On:
    return On(pred, g)


def nemesis_gen(nem: GenLike, clients: GenLike = None) -> Generator:
    """Nemesis process sees ``nem``; clients see ``clients`` (upstream
    two-arity ``gen/nemesis``)."""
    if clients is None:
        return On(lambda p: p == NEMESIS, nem)
    return Partition({True: gen(nem), False: gen(clients)},
                     lambda p: p == NEMESIS)


def clients_gen(cli: GenLike, nem: GenLike = None) -> Generator:
    """Clients see ``cli``; nemesis sees ``nem`` (upstream
    ``gen/clients``)."""
    if nem is None:
        return On(lambda p: p != NEMESIS, cli)
    return Partition({True: gen(nem), False: gen(cli)},
                     lambda p: p == NEMESIS)


class Partition(Generator):
    """Dispatch on ``key_fn(process)`` to a table of sub-generators."""

    def __init__(self, table: Dict[Any, Generator],
                 key_fn: Callable[[Any], Any]):
        self._table = table
        self._key_fn = key_fn

    def op(self, test, process):
        g = self._table.get(self._key_fn(process))
        return None if g is None else g.op(test, process)


class Each(Generator):
    """A fresh generator (from ``factory``) per process — every process
    sees the whole sequence (upstream ``gen/each``)."""

    def __init__(self, factory: Callable[[], GenLike]):
        self._factory = factory
        self._per: Dict[Any, Generator] = {}
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            g = self._per.get(process)
            if g is None:
                g = self._per[process] = gen(self._factory())
        return g.op(test, process)


def each(factory: Callable[[], GenLike]) -> Each:
    return Each(factory)


class FilterOps(Generator):
    """Only ops whose sketch satisfies ``pred`` pass through (upstream
    ``gen/filter``)."""

    def __init__(self, pred: Callable[[Dict[str, Any]], bool], g: GenLike):
        self._pred = pred
        self._gen = gen(g)

    def op(self, test, process):
        while True:
            sketch = self._gen.op(test, process)
            if sketch is None or self._pred(sketch):
                return sketch


def filter_ops(pred: Callable[[Dict[str, Any]], bool], g: GenLike) -> FilterOps:
    return FilterOps(pred, g)


class FMap(Generator):
    """Transform each emitted sketch (upstream ``gen/map`` /
    value-rewriting helpers)."""

    def __init__(self, f: Callable[[Dict[str, Any]], Dict[str, Any]],
                 g: GenLike):
        self._f = f
        self._gen = gen(g)

    def op(self, test, process):
        sketch = self._gen.op(test, process)
        return None if sketch is None else self._f(sketch)


def fmap(f: Callable[[Dict[str, Any]], Dict[str, Any]], g: GenLike) -> FMap:
    return FMap(f, g)


class Log(Generator):
    """Log a message once, then exhaust (upstream ``gen/log``)."""

    def __init__(self, msg: str):
        self._msg = msg
        self._lock = threading.Lock()
        self._done = False

    def op(self, test, process):
        with self._lock:
            if not self._done:
                log.info("%s", self._msg)
                self._done = True
        return None


def log_gen(msg: str) -> Log:
    return Log(msg)


class Synchronize(Generator):
    """Barrier: no client process proceeds into ``g`` until every active
    client process has exhausted whatever preceded this generator and
    arrived here (upstream ``gen/synchronize``). The runner declares the
    worker set via ``test["active-processes"]`` (a live set maintained by
    :mod:`jepsen_tpu.core`); the nemesis is excluded — it never routes
    through client-side barriers. Without an active set, the first
    arrival passes."""

    def __init__(self, g: GenLike):
        self._gen = gen(g)
        self._arrived: set = set()
        self._open = False
        self._cond = threading.Condition()

    def op(self, test, process):
        active = test.get("active-processes") if hasattr(test, "get") else None
        if active and process != NEMESIS:
            with self._cond:
                self._arrived.add(process)
                while not self._open:
                    want = {p for p in active() if p != NEMESIS}
                    if self._arrived >= want:
                        break
                    # wait with timeout: the active set shrinks as workers
                    # exit, so re-check periodically
                    self._cond.wait(timeout=0.05)
                self._open = True
                self._cond.notify_all()
        return self._gen.op(test, process)


def synchronize(g: GenLike) -> Synchronize:
    return Synchronize(g)


def phases(*gens: GenLike) -> Seq:
    """Each phase runs to global exhaustion before the next begins; every
    phase is barrier-synchronized (upstream ``gen/phases``)."""
    return Seq([Synchronize(g) for g in gens])


def then(a: GenLike, b: GenLike) -> Seq:
    """``b`` after ``a`` (upstream ``gen/then``, reversed args)."""
    return Seq([a, b])


# -- stock workload sketches --------------------------------------------------

def r() -> Dict[str, Any]:
    return {"f": "read", "value": None}


def w(rng: Optional[random.Random] = None, hi: int = 5) -> Dict[str, Any]:
    return {"f": "write", "value": (rng or random).randint(0, hi - 1)}


def cas(rng: Optional[random.Random] = None, hi: int = 5) -> Dict[str, Any]:
    rng = rng or random
    return {"f": "cas", "value": [rng.randint(0, hi - 1),
                                  rng.randint(0, hi - 1)]}


def register_workload(hi: int = 5, seed: Optional[int] = None) -> Mix:
    """The classic etcd-style r/w/cas mix."""
    rng = random.Random(seed)
    return Mix([Fn(lambda: r()), Fn(lambda: w(rng, hi)),
                Fn(lambda: cas(rng, hi))], seed=seed)


class UniqueValues(Generator):
    """Emit ``{"f": f, "value": n}`` with ``n`` unique and increasing —
    the stock source for set-add / enqueue workloads whose checkers
    account for each attempted value individually."""

    def __init__(self, f: str):
        self._f = f
        self._counter = itertools.count()
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            return {"f": self._f, "value": next(self._counter)}


def unique_values(f: str) -> UniqueValues:
    return UniqueValues(f)


class TxnWorkload(Generator):
    """Elle-style list-append transactions (upstream
    ``jepsen.tests.cycle.append``): each op is ``{"f": "txn", "value":
    [["append", k, v], ["r", k, None], ...]}`` — 1..``max_len``
    micro-ops over ``keys`` keys, appends carrying per-key UNIQUE
    increasing values (the traceability precondition the inference
    depends on; uniqueness is guarded by one lock across workers).
    ``single_key=True`` confines every txn to one key (the CAS-based
    etcd/redis tiers commit a txn as one per-key compare-and-set)."""

    def __init__(self, keys: int = 3, max_len: int = 4,
                 read_p: float = 0.5, seed: Optional[int] = None,
                 key_prefix: str = "t", single_key: bool = False):
        self._keys = [f"{key_prefix}{i}" for i in range(keys)]
        self._max_len = max(1, max_len)
        self._read_p = read_p
        self._rng = random.Random(seed)
        self._next: Dict[str, int] = {k: 0 for k in self._keys}
        self._single = single_key
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            rng = self._rng
            n = rng.randint(1, self._max_len)
            if self._single:
                pool = [rng.choice(self._keys)] * n
            else:
                pool = [rng.choice(self._keys) for _ in range(n)]
            micros = []
            for k in pool:
                if rng.random() < self._read_p:
                    micros.append(["r", k, None])
                else:
                    v = self._next[k]
                    self._next[k] = v + 1
                    micros.append(["append", k, v])
            return {"f": "txn", "value": micros}


def txn_workload(keys: int = 3, max_len: int = 4, read_p: float = 0.5,
                 seed: Optional[int] = None,
                 single_key: bool = False) -> TxnWorkload:
    return TxnWorkload(keys=keys, max_len=max_len, read_p=read_p,
                       seed=seed, single_key=single_key)


# -- independent-keys generators (upstream jepsen.independent) ---------------

class SequentialKeys(Generator):
    """One key at a time: serve ``factory(key)`` wrapped as ``[key, v]``
    values until exhausted, then the next key (upstream
    ``independent/sequential-generator``)."""

    def __init__(self, keys: Iterable, factory: Callable[[Any], GenLike]):
        self._keys = iter(keys)
        self._factory = factory
        self._cur: Optional[Generator] = None
        self._key: Any = None
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            while True:
                if self._cur is not None:
                    sketch = self._cur.op(test, process)
                    if sketch is not None:
                        if "f" in sketch:
                            sketch = dict(sketch)
                            sketch["value"] = [self._key,
                                               sketch.get("value")]
                        return sketch
                    self._cur = None
                try:
                    self._key = next(self._keys)
                except StopIteration:
                    return None
                self._cur = gen(self._factory(self._key))


def sequential_generator(keys: Iterable,
                         factory: Callable[[Any], GenLike]) -> SequentialKeys:
    return SequentialKeys(keys, factory)


class ConcurrentKeys(Generator):
    """``n`` keys served concurrently, each by a dedicated group of
    processes (upstream ``independent/concurrent-generator``). Processes
    are assigned to groups by ``process % n`` (nemesis excluded); when a
    key's generator exhausts, its group moves to the next key."""

    def __init__(self, n: int, keys: Iterable,
                 factory: Callable[[Any], GenLike]):
        self._n = n
        self._keys = iter(keys)
        self._factory = factory
        self._groups: Dict[int, Optional[Dict[str, Any]]] = {}
        self._lock = threading.Lock()

    def _fresh(self):
        try:
            key = next(self._keys)
        except StopIteration:
            return None
        return {"key": key, "gen": gen(self._factory(key))}

    def op(self, test, process):
        if process == NEMESIS:
            return None
        group = int(process) % self._n
        while True:
            with self._lock:
                if group not in self._groups:
                    self._groups[group] = self._fresh()
                slot = self._groups[group]
            if slot is None:
                return None
            sketch = slot["gen"].op(test, process)
            if sketch is not None:
                if "f" in sketch:
                    sketch = dict(sketch)
                    sketch["value"] = [slot["key"], sketch.get("value")]
                return sketch
            with self._lock:
                if self._groups.get(group) is slot:
                    self._groups[group] = self._fresh()


def concurrent_generator(n: int, keys: Iterable,
                         factory: Callable[[Any], GenLike]) -> ConcurrentKeys:
    return ConcurrentKeys(n, keys, factory)
