"""DB automation protocols — upstream ``jepsen/src/jepsen/db.clj``
(SURVEY.md §2.1, L1): install/start/stop the system under test on each
node.

Protocols (duck-typed; implement what applies, like upstream's optional
``Primary``/``LogFiles`` protocols):

- ``setup(test, node)`` / ``teardown(test, node)`` — required.
- ``primaries(test)`` / ``setup_primary(test, node)`` — Primary.
- ``log_files(test, node)`` — LogFiles; paths are downloaded by
  ``snarf_logs`` at the end of a run.
- ``pause/resume/kill/start`` — Process (drives the kill/pause nemeses).
"""
from __future__ import annotations

import os
from typing import Any, List, Mapping, Optional, Sequence

from jepsen_tpu import control


class DB:
    """Base DB (upstream ``jepsen.db/DB`` protocol)."""

    def setup(self, test: Mapping, node: str) -> None:
        pass

    def teardown(self, test: Mapping, node: str) -> None:
        pass

    # -- LogFiles ------------------------------------------------------------
    def log_files(self, test: Mapping, node: str) -> List[str]:
        return []

    # -- Primary -------------------------------------------------------------
    def primaries(self, test: Mapping) -> List[str]:
        return []

    # -- Process (for kill/pause nemeses) -------------------------------------
    def kill(self, test: Mapping, node: str) -> None:
        raise NotImplementedError

    def start(self, test: Mapping, node: str) -> None:
        raise NotImplementedError

    def pause(self, test: Mapping, node: str) -> None:
        raise NotImplementedError

    def resume(self, test: Mapping, node: str) -> None:
        raise NotImplementedError


class NoopDB(DB):
    """No database to set up (upstream ``jepsen.db/noop``)."""


def noop() -> NoopDB:
    return NoopDB()


def cycle_db(db: DB, test: Mapping, node: str) -> None:
    """Teardown then setup (upstream ``jepsen.db/cycle!``)."""
    db.teardown(test, node)
    db.setup(test, node)


def setup_all(test: Mapping) -> None:
    """Run ``db.setup`` on every node in parallel (called by the core
    runner; upstream ``core/run!`` via ``on-nodes``)."""
    db = test.get("db")
    if db is None:
        return
    control.on_nodes(test, lambda s, node: db.setup(test, node))
    for node in db.primaries(test):
        if hasattr(db, "setup_primary"):
            db.setup_primary(test, node)


def teardown_all(test: Mapping) -> None:
    db = test.get("db")
    if db is None:
        return
    control.on_nodes(test, lambda s, node: db.teardown(test, node))


def snarf_logs(test: Mapping, dest_dir: str) -> List[str]:
    """Download every node's DB log files into ``dest_dir/<node>/``
    (upstream ``core/snarf-logs!``)."""
    db = test.get("db")
    if db is None:
        return []
    got: List[str] = []

    def grab(s: control.Session, node: str) -> None:
        for path in db.log_files(test, node):
            local_dir = os.path.join(dest_dir, str(node))
            os.makedirs(local_dir, exist_ok=True)
            local = os.path.join(local_dir, os.path.basename(path))
            try:
                s.download(path, local)
                got.append(local)
            except Exception:                           # noqa: BLE001
                pass                                    # missing log ≠ failure

    control.on_nodes(test, grab)
    return got
