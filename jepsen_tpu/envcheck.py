"""Runtime companion of jtlint's env-gate registry: warn once on set
``JEPSEN_TPU_*`` environment variables the tree does not read.

Today a typo'd opt-out (``JEPSEN_TPU_NO_WORDWALK=1`` for
``JEPSEN_TPU_NO_WORD_WALK=1``) silently no-ops — the worst failure
mode an escape hatch can have. The static analyzer generates the
authoritative gate registry (``data/env_gates.json``, kept current by
the CI ``lint`` job); this module compares it against the live
environment at facade/daemon/CLI entry and, once per process:

- logs one warning naming each unknown gate (with the closest known
  name when one is near), and
- bumps ``obs.count("env.unknown_gate")`` per unknown gate, so the
  condition is visible on ``GET /metrics`` too.

Checking never fails the caller: a missing/corrupt registry (e.g. an
installed package without the repo ``data/`` tree) disables the check
rather than breaking real work.
"""
from __future__ import annotations

import difflib
import json
import logging
import os
import threading
from typing import List, Optional, Set

from jepsen_tpu import obs

log = logging.getLogger("jepsen.envcheck")

_PREFIX = "JEPSEN_TPU_"
_REGISTRY = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         os.pardir, "data", "env_gates.json")

_lock = threading.Lock()
_checked = False


def known_gates(path: Optional[str] = None) -> Optional[Set[str]]:
    """The registry's gate names, or None when it is unavailable
    (check disabled, never an error)."""
    try:
        with open(path or _REGISTRY, encoding="utf-8") as f:
            gates = json.load(f).get("gates")
        if not isinstance(gates, dict) or not gates:
            return None
        return set(gates)
    except (OSError, ValueError):
        return None


def unknown_gates(path: Optional[str] = None) -> List[str]:
    """Set ``JEPSEN_TPU_*`` env vars absent from the registry (empty
    when the registry is unavailable)."""
    known = known_gates(path)
    if known is None:
        return []
    return sorted(k for k in os.environ
                  if k.startswith(_PREFIX) and k not in known)


def check_once(path: Optional[str] = None,
               force: bool = False) -> List[str]:
    """Warn-once entry hook (facade / check-serve daemon / CLI): logs
    and counts each set-but-unknown gate on the first call, a cheap
    no-op afterwards. Returns the unknown names (tests use this)."""
    global _checked
    with _lock:
        if _checked and not force:
            return []
        _checked = True
    unknown = unknown_gates(path)
    if not unknown:
        return []
    known = known_gates(path) or set()
    for name in unknown:
        obs.count("env.unknown_gate")
        close = difflib.get_close_matches(name, known, n=1)
        hint = f" (did you mean {close[0]}?)" if close else ""
        log.warning("unknown JEPSEN_TPU_* gate %s is set and has no "
                    "effect%s — known gates are registered in "
                    "data/env_gates.json", name, hint)
    return unknown
