"""Host-side dependency inference for list-append histories — Elle's
traceability trick (VLDB 2020 §4): because every append is unique and
reads return the WHOLE list, any read of key ``k`` reveals a prefix of
``k``'s total append order. The longest observed read per key is the
recovered order; from it the three dependency edge families fall out:

- ``ww``  — writer of ``order[i]`` → writer of ``order[i+1]``
  (consecutive appends in the recovered order);
- ``wr``  — writer of a read version's LAST element → the reader
  (earlier elements are implied through ww);
- ``rw``  — the reader of a length-``L`` prefix → writer of
  ``order[L]`` (the append the read missed), the anti-dependency.

Appends never observed by any read have no recoverable position:
their edges are NOT emitted (documented-weaker inference, counted as
``txn.infer.ambiguous_appends`` in obs — never silent). Reads that are
not prefix-compatible with the recovered order, reads of values never
appended, and duplicate appends of one value are DIRECT anomalies
(``incompatible-order`` / ``duplicate-append``); a read observing a
``fail`` txn's append is a G1a aborted read. Crashed (``info``) txns'
appends count only when some read proves they took effect
(``txn.infer.crashed_recovered``); unproven ones stay out
(``txn.infer.crashed_unresolved``).

The output is a COO edge tensor (:class:`DepGraph`) in the narrow
``transfer.idx_dtype`` dtypes — the exact operand
:mod:`jepsen_tpu.txn.cycles` turns into bit-packed adjacency for the
device closure.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.txn import ops as txn_ops
from jepsen_tpu.util import hashable

# edge-type codes, also the COO ``et`` values
WW, WR, RW = 0, 1, 2
EDGE_NAMES = ("ww", "wr", "rw")


@dataclass(frozen=True)
class DepGraph:
    """Transaction dependency graph in COO form. ``src``/``dst`` index
    the kept txns (``txns[tid]``), ``et`` is the edge type code."""
    n: int
    src: np.ndarray          # idx[e]
    dst: np.ndarray          # idx[e]
    et: np.ndarray           # i8[e]
    txns: Tuple[txn_ops.Txn, ...]
    direct: Tuple[Dict[str, Any], ...] = ()   # inference-time anomalies
    counters: Dict[str, int] = field(default_factory=dict)

    @property
    def e(self) -> int:
        return int(len(self.src))

    def edge_counts(self) -> Dict[str, int]:
        return {EDGE_NAMES[t]: int((self.et == t).sum())
                for t in (WW, WR, RW)}


def _bump(counters: Dict[str, int], name: str, n: int = 1) -> None:
    if n:
        counters[name] = counters.get(name, 0) + n
        obs.count(f"txn.infer.{name}", n)


def infer(txns: Sequence[txn_ops.Txn],
          fails: Sequence[txn_ops.FailedTxn] = ()) -> DepGraph:
    """Recover per-key append orders and emit the wr/ww/rw COO edges."""
    from jepsen_tpu.checkers import transfer

    counters: Dict[str, int] = {}
    direct: List[Dict[str, Any]] = []

    # per-key value -> appender tid; duplicates are a direct anomaly
    # (Elle's uniqueness precondition — without it traceability dies)
    appenders: Dict[Any, Dict[Any, int]] = {}
    crashed_append: Set[Tuple[Any, Any]] = set()
    for t in txns:
        for kind, k, v in t.micros:
            if kind != txn_ops.APPEND:
                continue
            hk, hv = hashable(k), hashable(v)
            per_key = appenders.setdefault(hk, {})
            if hv in per_key:
                direct.append({"type": "duplicate-append", "key": k,
                               "value": v,
                               "txns": [per_key[hv], t.tid]})
                _bump(counters, "duplicate_append")
                continue
            per_key[hv] = t.tid
            if t.crashed:
                crashed_append.add((hk, hv))
    failed_append: Dict[Tuple[Any, Any], int] = {}
    for f in fails:
        for kind, k, v in f.micros:
            if kind == txn_ops.APPEND:
                failed_append.setdefault((hashable(k), hashable(v)),
                                         f.op.index)

    # reads per key (crashed txns' reads were blanked in collect())
    reads: Dict[Any, List[Tuple[int, Tuple[Any, ...]]]] = {}
    keys_seen: List[Any] = []
    for t in txns:
        for kind, k, v in t.micros:
            hk = hashable(k)
            if hk not in reads:
                reads[hk] = []
                keys_seen.append(hk)
            if kind == txn_ops.READ and v is not None:
                reads[hk].append((t.tid, tuple(hashable(x) for x in v)))

    edges: Set[Tuple[int, int, int]] = set()

    def _edge(u: int, v: int, et: int) -> None:
        if u != v:                      # self-deps carry no cycle info
            edges.add((u, v, et))

    n_ambiguous = 0
    n_crash_recovered = 0
    for hk in keys_seen:
        rds = reads[hk]
        # recovered order: the longest observed version of this key
        order: Tuple[Any, ...] = ()
        for _tid, vs in rds:
            if len(vs) > len(order):
                order = vs
        ok_order = True
        if len(set(order)) != len(order):
            direct.append({"type": "incompatible-order", "key": hk,
                           "cause": "duplicate value in one read",
                           "version": list(order)})
            _bump(counters, "incompatible_order")
            ok_order = False
        for tid_r, vs in rds:
            if vs != order[:len(vs)]:
                direct.append({"type": "incompatible-order", "key": hk,
                               "cause": "read is not a prefix of the "
                                        "recovered order",
                               "txn": tid_r, "version": list(vs),
                               "order": list(order)})
                _bump(counters, "incompatible_order")
                ok_order = False
        writers: List[Optional[int]] = []
        per_key = appenders.get(hk, {})
        for v in order:
            w = per_key.get(v)
            if w is None:
                if (hk, v) in failed_append:
                    direct.append({"type": "G1a", "key": hk, "value": v,
                                   "failed-op-index":
                                       failed_append[(hk, v)]})
                    _bump(counters, "aborted_read")
                else:
                    direct.append({"type": "incompatible-order",
                                   "key": hk, "value": v,
                                   "cause": "read observed a value "
                                            "never appended"})
                    _bump(counters, "phantom_value")
                ok_order = False
                writers.append(None)
            else:
                if (hk, v) in crashed_append:
                    n_crash_recovered += 1
                writers.append(w)
        # appends with no recovered position: weaker inference, counted
        observed = set(order)
        n_ambiguous += sum(1 for v2 in per_key if v2 not in observed)
        if not ok_order:
            # the recovered order is untrustworthy: emitting edges from
            # it could fabricate cycles — the direct anomalies above
            # carry the verdict for this key
            continue
        for i in range(len(writers) - 1):
            a, b = writers[i], writers[i + 1]
            if a is not None and b is not None:
                _edge(a, b, WW)
        for tid_r, vs in rds:
            if vs:
                w = writers[len(vs) - 1]
                if w is not None:
                    _edge(w, tid_r, WR)
            if len(vs) < len(writers):
                w = writers[len(vs)]
                if w is not None:
                    _edge(tid_r, w, RW)

    observed_by_key: Dict[Any, Set[Any]] = {
        hk: {v for _t, vs in reads[hk] for v in vs} for hk in keys_seen}
    _bump(counters, "ambiguous_appends", n_ambiguous)
    _bump(counters, "crashed_recovered", n_crash_recovered)
    _bump(counters, "crashed_unresolved",
          sum(1 for (hk, hv) in crashed_append
              if hv not in observed_by_key.get(hk, ())))

    n = len(txns)
    dt = transfer.idx_dtype(max(n, 1), count=False)
    if edges:
        es = sorted(edges)
        src = np.asarray([e[0] for e in es], dt)
        dst = np.asarray([e[1] for e in es], dt)
        et = np.asarray([e[2] for e in es], np.int8)
    else:
        src = np.zeros(0, dt)
        dst = np.zeros(0, dt)
        et = np.zeros(0, np.int8)
    for t in (WW, WR, RW):
        obs.count(f"txn.edges.{EDGE_NAMES[t]}", int((et == t).sum()))
    return DepGraph(n=n, src=src, dst=dst, et=et, txns=tuple(txns),
                    direct=tuple(direct), counters=counters)
