"""Host-side dependency inference for list-append histories — Elle's
traceability trick (VLDB 2020 §4): because every append is unique and
reads return the WHOLE list, any read of key ``k`` reveals a prefix of
``k``'s total append order. The longest observed read per key is the
recovered order; from it the three dependency edge families fall out:

- ``ww``  — writer of ``order[i]`` → writer of ``order[i+1]``
  (consecutive appends in the recovered order);
- ``wr``  — writer of a read version's LAST element → the reader
  (earlier elements are implied through ww);
- ``rw``  — the reader of a length-``L`` prefix → writer of
  ``order[L]`` (the append the read missed), the anti-dependency.

Appends never observed by any read have no recoverable position:
their edges are NOT emitted (documented-weaker inference, counted as
``txn.infer.ambiguous_appends`` in obs — never silent). Reads that are
not prefix-compatible with the recovered order, reads of values never
appended, and duplicate appends of one value are DIRECT anomalies
(``incompatible-order`` / ``duplicate-append``); a read observing a
``fail`` txn's append is a G1a aborted read. Crashed (``info``) txns'
appends count only when some read proves they took effect
(``txn.infer.crashed_recovered``); unproven ones stay out
(``txn.infer.crashed_unresolved``).

The output is a COO edge tensor (:class:`DepGraph`) in the narrow
``transfer.idx_dtype`` dtypes — the exact operand
:mod:`jepsen_tpu.txn.cycles` turns into bit-packed adjacency for the
device closure.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.txn import ops as txn_ops
from jepsen_tpu.util import hashable, hashable_seq

# edge-type codes, also the COO ``et`` values
WW, WR, RW = 0, 1, 2
EDGE_NAMES = ("ww", "wr", "rw")

# commit-order pseudo-edge code — NOT a DepGraph edge type (post-hoc
# inference never stores it; it is derived from txn intervals), but the
# lattice closure's fourth lane speaks it on the incremental wire
CM = 3


def commit_mask(txns: Sequence[txn_ops.Txn]) -> np.ndarray:
    """Dense commit-order mask for the snapshot-isolation lattice
    level: ``cm[i, j]`` is True when txn ``i`` committed strictly
    before txn ``j`` began (``end_i < start_j`` over history op
    indices). Crashed txns have no commit point (``end == -1``) and
    emit no cm out-edges — a txn that never committed cannot be
    "first committer" against anyone. cm is transitive by
    construction (every txn's start precedes its own commit), so the
    closure lane that mixes it with ww/wr needs no extra pass."""
    n = len(txns)
    if n == 0:
        return np.zeros((0, 0), bool)
    start = np.asarray([t.index for t in txns], np.int64)
    end = np.asarray([t.end for t in txns], np.int64)
    cm = (end >= 0)[:, None] & (end[:, None] < start[None, :])
    np.fill_diagonal(cm, False)
    return cm


@dataclass(frozen=True)
class DepGraph:
    """Transaction dependency graph in COO form. ``src``/``dst`` index
    the kept txns (``txns[tid]``), ``et`` is the edge type code."""
    n: int
    src: np.ndarray          # idx[e]
    dst: np.ndarray          # idx[e]
    et: np.ndarray           # i8[e]
    txns: Tuple[txn_ops.Txn, ...]
    direct: Tuple[Dict[str, Any], ...] = ()   # inference-time anomalies
    counters: Dict[str, int] = field(default_factory=dict)

    @property
    def e(self) -> int:
        return int(len(self.src))

    def edge_counts(self) -> Dict[str, int]:
        return {EDGE_NAMES[t]: int((self.et == t).sum())
                for t in (WW, WR, RW)}


def _bump(counters: Dict[str, int], name: str, n: int = 1) -> None:
    if n:
        counters[name] = counters.get(name, 0) + n
        obs.count(f"txn.infer.{name}", n)


def infer(txns: Sequence[txn_ops.Txn],
          fails: Sequence[txn_ops.FailedTxn] = ()) -> DepGraph:
    """Recover per-key append orders and emit the wr/ww/rw COO edges."""
    from jepsen_tpu.checkers import transfer

    counters: Dict[str, int] = {}
    direct: List[Dict[str, Any]] = []

    # list-append keys/values are almost always flat str/int — skip
    # the deep-freeze isinstance cascade for them (it was ~10% of the
    # 100k rung's host wall); ``hashable`` is the identity on both
    def _h(x, _hashable=hashable):
        return x if type(x) is str or type(x) is int else _hashable(x)

    # per-key value -> appender tid; duplicates are a direct anomaly
    # (Elle's uniqueness precondition — without it traceability dies)
    appenders: Dict[Any, Dict[Any, int]] = {}
    crashed_append: Set[Tuple[Any, Any]] = set()
    for t in txns:
        for kind, k, v in t.micros:
            if kind != txn_ops.APPEND:
                continue
            hk, hv = _h(k), _h(v)
            per_key = appenders.setdefault(hk, {})
            if hv in per_key:
                direct.append({"type": "duplicate-append", "key": k,
                               "value": v,
                               "txns": [per_key[hv], t.tid]})
                _bump(counters, "duplicate_append")
                continue
            per_key[hv] = t.tid
            if t.crashed:
                crashed_append.add((hk, hv))
    failed_append: Dict[Tuple[Any, Any], int] = {}
    for f in fails:
        for kind, k, v in f.micros:
            if kind == txn_ops.APPEND:
                failed_append.setdefault((_h(k), _h(v)),
                                         f.op.index)

    # reads per key (crashed txns' reads were blanked in collect())
    reads: Dict[Any, List[Tuple[int, Tuple[Any, ...]]]] = {}
    keys_seen: List[Any] = []
    for t in txns:
        for kind, k, v in t.micros:
            hk = _h(k)
            if hk not in reads:
                reads[hk] = []
                keys_seen.append(hk)
            if kind == txn_ops.READ and v is not None:
                # hashable_seq: the deep-freeze per element was ~80%
                # of infer at the 100k rung; flat reads skip it
                reads[hk].append((t.tid, hashable_seq(v)))

    edges: Set[Tuple[int, int, int]] = set()

    def _edge(u: int, v: int, et: int) -> None:
        if u != v:                      # self-deps carry no cycle info
            edges.add((u, v, et))

    n_ambiguous = 0
    n_crash_recovered = 0
    for hk in keys_seen:
        rds = reads[hk]
        # recovered order: the longest observed version of this key
        order: Tuple[Any, ...] = ()
        for _tid, vs in rds:
            if len(vs) > len(order):
                order = vs
        ok_order = True
        if len(set(order)) != len(order):
            direct.append({"type": "incompatible-order", "key": hk,
                           "cause": "duplicate value in one read",
                           "version": list(order)})
            _bump(counters, "incompatible_order")
            ok_order = False
        for tid_r, vs in rds:
            if vs != order[:len(vs)]:
                direct.append({"type": "incompatible-order", "key": hk,
                               "cause": "read is not a prefix of the "
                                        "recovered order",
                               "txn": tid_r, "version": list(vs),
                               "order": list(order)})
                _bump(counters, "incompatible_order")
                ok_order = False
        writers: List[Optional[int]] = []
        per_key = appenders.get(hk, {})
        for v in order:
            w = per_key.get(v)
            if w is None:
                if (hk, v) in failed_append:
                    direct.append({"type": "G1a", "key": hk, "value": v,
                                   "failed-op-index":
                                       failed_append[(hk, v)]})
                    _bump(counters, "aborted_read")
                else:
                    direct.append({"type": "incompatible-order",
                                   "key": hk, "value": v,
                                   "cause": "read observed a value "
                                            "never appended"})
                    _bump(counters, "phantom_value")
                ok_order = False
                writers.append(None)
            else:
                if (hk, v) in crashed_append:
                    n_crash_recovered += 1
                writers.append(w)
        # appends with no recovered position: weaker inference, counted
        observed = set(order)
        n_ambiguous += sum(1 for v2 in per_key if v2 not in observed)
        if not ok_order:
            # the recovered order is untrustworthy: emitting edges from
            # it could fabricate cycles — the direct anomalies above
            # carry the verdict for this key
            continue
        for i in range(len(writers) - 1):
            a, b = writers[i], writers[i + 1]
            if a is not None and b is not None:
                _edge(a, b, WW)
        for tid_r, vs in rds:
            if vs:
                w = writers[len(vs) - 1]
                if w is not None:
                    _edge(w, tid_r, WR)
            if len(vs) < len(writers):
                w = writers[len(vs)]
                if w is not None:
                    _edge(tid_r, w, RW)

    observed_by_key: Dict[Any, Set[Any]] = {
        hk: {v for _t, vs in reads[hk] for v in vs} for hk in keys_seen}
    _bump(counters, "ambiguous_appends", n_ambiguous)
    _bump(counters, "crashed_recovered", n_crash_recovered)
    _bump(counters, "crashed_unresolved",
          sum(1 for (hk, hv) in crashed_append
              if hv not in observed_by_key.get(hk, ())))

    n = len(txns)
    dt = transfer.idx_dtype(max(n, 1), count=False)
    if edges:
        es = np.array(sorted(edges), np.int64)     # one pass, [E, 3]
        src = es[:, 0].astype(dt)
        dst = es[:, 1].astype(dt)
        et = es[:, 2].astype(np.int8)
    else:
        src = np.zeros(0, dt)
        dst = np.zeros(0, dt)
        et = np.zeros(0, np.int8)
    for t in (WW, WR, RW):
        obs.count(f"txn.edges.{EDGE_NAMES[t]}", int((et == t).sum()))
    return DepGraph(n=n, src=src, dst=dst, et=et, txns=tuple(txns),
                    direct=tuple(direct), counters=counters)


# -- incremental inference (streaming check sessions) ---------------------
#
# The streaming-session analogue of :func:`infer`: ops arrive in append
# blocks, invocations may complete blocks later, and the dependency
# adjacency must GROW monotonically so the device closure
# (:class:`jepsen_tpu.txn.cycles.IncrementalClosure`) can re-close only
# the dirty row/column blocks per append. The settled-prefix discipline
# of checkers/online.py carries over: a read is *settled* — and only
# then allowed to extend the recovered order or emit edges — once every
# value it observed has a KNOWN appender (or is proven aborted, a G1a).
# Until then it waits: trusting it earlier could brand an in-flight
# append's value a phantom (a false alarm the post-hoc path can never
# produce, because post-hoc everything has completed). Under this rule
# the emitted edge set only ever grows in well-formed histories —
# recovered orders are append-only and prefix-validated, so a ww/wr/rw
# edge once emitted is never retracted — which is exactly what makes a
# sound early cycle alarm possible. At close,
# :meth:`IncrementalInfer.resolve_stragglers` resolves still-pending
# invocations as crashed and finalizes pending reads (a value still
# unattributed then IS a phantom), after which the edge set equals the
# post-hoc :func:`infer` edge set (differentially tested).


class _KeyState:
    """Per-key incremental traceability state."""

    __slots__ = ("order", "writers", "appenders", "crashed_vals",
                 "failed_vals", "readers_by_len", "pending", "poisoned")

    def __init__(self) -> None:
        self.order: List[Any] = []          # recovered append order
        self.writers: List[int] = []        # appender tid per position
        self.appenders: Dict[Any, int] = {}
        self.crashed_vals: Set[Any] = set()
        self.failed_vals: Dict[Any, int] = {}
        self.readers_by_len: Dict[int, List[int]] = {}
        self.pending: List[Tuple[int, Tuple[Any, ...]]] = []
        self.poisoned = False               # direct anomaly on this key


class IncrementalInfer:
    """Stateful list-append dependency inference for one session.

    Feed append blocks with :meth:`feed_block`; new COO edges since
    the last drain come from :meth:`drain_new_edges` (the device
    closure's per-append delta); :meth:`graph` materializes the full
    accumulated :class:`DepGraph` (host fallback + witness walk).
    Direct anomalies land in :attr:`direct` as they are proven."""

    def __init__(self) -> None:
        from jepsen_tpu.txn import ops as txn_ops
        self._ops_mod = txn_ops
        self.txns: List[Any] = []
        self.fails: List[Any] = []
        self._live: Dict[Any, Any] = {}     # proc -> invoke op
        self._keys: Dict[Any, _KeyState] = {}
        self.direct: List[Dict[str, Any]] = []
        self.counters: Dict[str, int] = {}
        self._edges: Set[Tuple[int, int, int]] = set()
        self._fresh: List[Tuple[int, int, int]] = []
        # stream positions drive the SI commit-order mask: every fed op
        # advances ``_pos`` (txn or not), so relative start/commit order
        # matches what post-hoc ``h.index`` would assign the same stream
        self._pos = 0
        self._live_start: Dict[Any, int] = {}   # proc -> invoke pos
        self.starts: List[int] = []             # per tid
        self.ends: List[int] = []               # per tid; -1 = crashed
        self._cm_fresh: List[Tuple[int, int]] = []

    # -- ingestion -------------------------------------------------------
    def feed_block(self, ops: Sequence[Any]) -> None:
        """Pair txn invocations/completions across block boundaries
        and run settled inference over the completions."""
        txn_ops = self._ops_mod
        for op in ops:
            pos = self._pos
            self._pos += 1
            if op.process == "nemesis" or op.f != "txn":
                continue
            if op.type == "invoke":
                self._live[op.process] = op
                self._live_start[op.process] = pos
                continue
            inv = self._live.pop(op.process, None)
            if inv is None:
                continue                    # completion without invoke
            start = self._live_start.pop(op.process, pos)
            if op.type == "fail":
                self.fails.append(txn_ops.FailedTxn(
                    op=inv, micros=tuple(txn_ops.micro_ops(inv.value))))
                self._register_fail(self.fails[-1])
            elif op.type == "ok":
                value = op.value if op.value is not None else inv.value
                self._add_txn(inv.with_(value=value),
                              tuple(txn_ops.micro_ops(value)),
                              crashed=False, start=start, end=pos)
            elif op.type == "info":
                micros = tuple(
                    (k, key, None) if k == txn_ops.READ else (k, key, v)
                    for k, key, v in txn_ops.micro_ops(inv.value))
                self._add_txn(inv, micros, crashed=True, start=start)

    def resolve_stragglers(self) -> None:
        """The stream is over: still-pending invocations resolve as
        crashed (reads blanked, exactly like post-hoc ``collect``),
        then still-pending reads finalize — a value with no appender
        now is a genuine phantom / aborted read."""
        txn_ops = self._ops_mod
        for p, inv in sorted(self._live.items(),
                             key=lambda kv: kv[1].index):
            micros = tuple(
                (k, key, None) if k == txn_ops.READ else (k, key, v)
                for k, key, v in txn_ops.micro_ops(inv.value))
            self._add_txn(inv, micros, crashed=True,
                          start=self._live_start.get(p, self._pos))
        self._live.clear()
        self._live_start.clear()
        for hk, ks in self._keys.items():
            still = ks.pending
            ks.pending = []
            for tid_r, vs in still:
                self._finalize_read(hk, ks, tid_r, vs, final=True)

    # -- internals -------------------------------------------------------
    def _bump(self, name: str, n: int = 1) -> None:
        _bump(self.counters, name, n)

    def _key(self, k: Any) -> _KeyState:
        hk = hashable(k)
        ks = self._keys.get(hk)
        if ks is None:
            ks = self._keys[hk] = _KeyState()
        return ks

    def _register_fail(self, f: Any) -> None:
        from jepsen_tpu.txn.ops import APPEND
        for kind, k, v in f.micros:
            if kind == APPEND:
                ks = self._key(k)
                ks.failed_vals.setdefault(hashable(v), f.op.index)

    def _add_txn(self, op: Any, micros: Tuple, crashed: bool,
                 start: int = -1, end: int = -1) -> None:
        from jepsen_tpu.txn.ops import APPEND, READ, Txn
        tid = len(self.txns)
        self.txns.append(Txn(tid=tid, op=op, micros=micros,
                             crashed=crashed, end=end))
        # commit-order in-edges: every txn added earlier committed (if
        # at all) at a smaller stream position, so the only NEW cm
        # edges a txn can bring are into itself — u→tid whenever u's
        # commit precedes this txn's start. O(n) vector scan per txn;
        # the dense-session cap bounds the quadratic total.
        if tid and start >= 0:
            ends = np.asarray(self.ends, np.int64)
            for u in np.nonzero((ends >= 0) & (ends < start))[0]:
                self._cm_fresh.append((int(u), tid))
        self.starts.append(start)
        self.ends.append(end)
        touched: List[Any] = []
        for kind, k, v in micros:
            hk = hashable(k)
            ks = self._key(k)
            if kind == APPEND:
                hv = hashable(v)
                if hv in ks.appenders:
                    self.direct.append(
                        {"type": "duplicate-append", "key": k,
                         "value": v, "txns": [ks.appenders[hv], tid]})
                    self._bump("duplicate_append")
                    ks.poisoned = True
                    continue
                ks.appenders[hv] = tid
                if crashed:
                    ks.crashed_vals.add(hv)
                touched.append(hk)
            elif kind == READ and v is not None:
                ks.pending.append((tid, hashable_seq(v)))
                touched.append(hk)
        # settlement: new appends may unblock reads queued on this key
        for hk in dict.fromkeys(touched):
            self._settle_key(hk, self._keys[hk])

    def _settle_key(self, hk: Any, ks: _KeyState) -> None:
        progressed = True
        while progressed:
            progressed = False
            still: List[Tuple[int, Tuple[Any, ...]]] = []
            for tid_r, vs in ks.pending:
                if all(v in ks.appenders for v in vs):
                    self._process_read(hk, ks, tid_r, vs)
                    progressed = True
                elif any(v in ks.failed_vals
                         and v not in ks.appenders for v in vs):
                    # a value only a FAILED txn ever appended: G1a
                    self._finalize_read(hk, ks, tid_r, vs)
                    progressed = True
                else:
                    still.append((tid_r, vs))
            ks.pending = still

    def _finalize_read(self, hk: Any, ks: _KeyState, tid_r: int,
                       vs: Tuple[Any, ...],
                       final: bool = False) -> None:
        """A read that can never settle cleanly: attribute each
        unknown value — G1a when a failed txn appended it, phantom
        when the stream is OVER and nobody did. Mid-stream
        (``final=False``, the G1a fast path) only the proven-aborted
        values are attributed: an unknown value may simply be an
        in-flight append, and branding it a phantom would diverge
        from the post-hoc reference. The key poisons either way
        (a proven G1a already fails the history)."""
        if all(v in ks.appenders for v in vs):
            self._process_read(hk, ks, tid_r, vs)
            return
        for v in vs:
            if v in ks.appenders:
                continue
            if v in ks.failed_vals:
                self.direct.append({"type": "G1a", "key": hk,
                                    "value": v,
                                    "failed-op-index":
                                        ks.failed_vals[v]})
                self._bump("aborted_read")
            elif final:
                self.direct.append(
                    {"type": "incompatible-order", "key": hk,
                     "value": v,
                     "cause": "read observed a value never appended"})
                self._bump("phantom_value")
        ks.poisoned = True

    def _edge(self, u: int, v: int, et: int) -> None:
        if u == v:
            return
        e = (u, v, et)
        if e not in self._edges:
            self._edges.add(e)
            self._fresh.append(e)
            obs.count(f"txn.edges.{EDGE_NAMES[et]}")

    def _process_read(self, hk: Any, ks: _KeyState, tid_r: int,
                      vs: Tuple[Any, ...]) -> None:
        """A settled read: validate prefix-compatibility, extend the
        recovered order, and emit the wr/ww/rw edges it proves."""
        if ks.poisoned:
            return
        L = len(vs)
        cur = ks.order
        if len(set(vs)) != L:
            self.direct.append(
                {"type": "incompatible-order", "key": hk,
                 "cause": "duplicate value in one read",
                 "version": list(vs)})
            self._bump("incompatible_order")
            ks.poisoned = True
            return
        if L > len(cur):
            if tuple(cur) != vs[:len(cur)]:
                self.direct.append(
                    {"type": "incompatible-order", "key": hk,
                     "txn": tid_r,
                     "cause": "read is not a prefix of the recovered "
                              "order",
                     "version": list(vs), "order": list(cur)})
                self._bump("incompatible_order")
                ks.poisoned = True
                return
            # extend: every value has a known appender (settled), so
            # the new positions' ww edges and the rw edges of readers
            # parked at the old frontier emit now
            for i in range(len(cur), L):
                hv = vs[i]
                w = ks.appenders[hv]
                if hv in ks.crashed_vals:
                    self._bump("crashed_recovered")
                ks.order.append(hv)
                ks.writers.append(w)
                if i > 0:
                    self._edge(ks.writers[i - 1], w, WW)
                for parked in ks.readers_by_len.pop(i, ()):
                    self._edge(parked, w, RW)
        elif tuple(vs) != tuple(cur[:L]):
            self.direct.append(
                {"type": "incompatible-order", "key": hk,
                 "txn": tid_r,
                 "cause": "read is not a prefix of the recovered "
                          "order",
                 "version": list(vs), "order": list(cur)})
            self._bump("incompatible_order")
            ks.poisoned = True
            return
        if L:
            self._edge(ks.writers[L - 1], tid_r, WR)
        if L < len(ks.order):
            self._edge(tid_r, ks.writers[L], RW)
        else:
            ks.readers_by_len.setdefault(L, []).append(tid_r)

    # -- views -----------------------------------------------------------
    @property
    def n(self) -> int:
        return len(self.txns)

    def pending_reads(self) -> int:
        return sum(len(ks.pending) for ks in self._keys.values())

    def drain_new_edges(self) -> Tuple[np.ndarray, np.ndarray,
                                       np.ndarray]:
        """Edges emitted since the last drain, as (src, dst, et)
        int32 arrays — the device closure's dirty-block delta."""
        fresh, self._fresh = self._fresh, []
        if not fresh:
            z = np.zeros(0, np.int32)
            return z, z.copy(), z.copy()
        arr = np.asarray(fresh, np.int64)
        return (arr[:, 0].astype(np.int32),
                arr[:, 1].astype(np.int32),
                arr[:, 2].astype(np.int32))

    def drain_new_cm(self) -> Tuple[np.ndarray, np.ndarray]:
        """Commit-order edges proven since the last drain, as
        (src, dst) int32 arrays — the lattice closure's fourth lane
        (:data:`CM`) delta. Separate from :meth:`drain_new_edges`
        because cm is not a :class:`DepGraph` edge type: the post-hoc
        path derives it from txn intervals (:func:`commit_mask`)."""
        fresh, self._cm_fresh = self._cm_fresh, []
        if not fresh:
            z = np.zeros(0, np.int32)
            return z, z.copy()
        arr = np.asarray(fresh, np.int64)
        return arr[:, 0].astype(np.int32), arr[:, 1].astype(np.int32)

    def intervals(self) -> Tuple[np.ndarray, np.ndarray]:
        """Per-tid (start, commit) stream positions — what the lattice
        host reference needs to rebuild :func:`commit_mask` exactly as
        the incremental cm lane saw it (incremental ``Txn.op.index``
        values are whatever the client sent; positions are ours)."""
        return (np.asarray(self.starts, np.int64),
                np.asarray(self.ends, np.int64))

    def graph(self) -> DepGraph:
        """The accumulated dependency graph (host fallback rungs and
        the witness walk read this)."""
        from jepsen_tpu.checkers import transfer

        n = len(self.txns)
        dt = transfer.idx_dtype(max(n, 1), count=False)
        if self._edges:
            es = sorted(self._edges)
            src = np.asarray([e[0] for e in es], dt)
            dst = np.asarray([e[1] for e in es], dt)
            et = np.asarray([e[2] for e in es], np.int8)
        else:
            src = np.zeros(0, dt)
            dst = np.zeros(0, dt)
            et = np.zeros(0, np.int8)
        return DepGraph(n=n, src=src, dst=dst, et=et,
                        txns=tuple(self.txns),
                        direct=tuple(self.direct),
                        counters=dict(self.counters))
