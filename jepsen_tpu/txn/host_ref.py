"""Host SCC reference for the transactional checker — the fallback
rung behind :mod:`jepsen_tpu.txn.cycles` (same contract as the dense
walks' host oracles: exactly one obs fallback routes here, verdicts
bit-identical). Iterative Tarjan over the COO dependency graph, the
Kahn trim that strips the acyclic fringe before a big graph meets the
dense device closure, and the deterministic witness walk BOTH engine
paths use to turn "a cycle exists in class X" into one concrete cycle
for the report.

The anomaly taxonomy maps to edge-type-restricted cycle predicates
(Adya / Elle):

- ``G0``       — a cycle using only ``ww`` edges (write cycle);
- ``G1c``      — a cycle in ``ww ∪ wr`` that is not already G0;
- ``G-single`` — a cycle with exactly one ``rw`` edge: some rw edge
  ``u → v`` with a ``ww ∪ wr`` path ``v ⇒ u``;
- ``G2``       — any remaining cycle (≥2 rw edges).

:func:`derive_anomalies` turns the four raw booleans into the reported
class list identically for the device and host paths, so differential
identity reduces to boolean agreement (tested in
``tests/test_txn.py``).
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from jepsen_tpu.txn.infer import CM, RW, WR, WW, DepGraph

# class name -> edge types allowed in its witness cycle
_CLASS_EDGES = {"G0": (WW,), "G1c": (WW, WR),
                "G-single": (WW, WR, RW), "G2": (WW, WR, RW)}


def _adj(graph: DepGraph, types: Sequence[int]
         ) -> List[List[Tuple[int, int]]]:
    """Adjacency lists restricted to ``types``: node -> sorted
    [(dst, et), ...] (sorted so every walk is deterministic)."""
    out: List[List[Tuple[int, int]]] = [[] for _ in range(graph.n)]
    tset = set(types)
    for u, v, t in zip(graph.src.tolist(), graph.dst.tolist(),
                       graph.et.tolist()):
        if t in tset:
            out[int(u)].append((int(v), int(t)))
    for lst in out:
        lst.sort()
    return out


def scc(n: int, adj: List[List[Tuple[int, int]]]) -> List[List[int]]:
    """Iterative Tarjan (100k-node graphs must not hit the recursion
    limit). Returns the strongly connected components, each sorted."""
    index = [-1] * n
    low = [0] * n
    on_stack = [False] * n
    stack: List[int] = []
    comps: List[List[int]] = []
    counter = 0
    for root in range(n):
        if index[root] >= 0:
            continue
        work: List[Tuple[int, int]] = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter
                counter += 1
                stack.append(v)
                on_stack[v] = True
            advanced = False
            for i in range(pi, len(adj[v])):
                w = adj[v][i][0]
                if index[w] < 0:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == v:
                        break
                comps.append(sorted(comp))
    return comps


def _has_cycle(n: int, adj: List[List[Tuple[int, int]]]) -> bool:
    return any(len(c) > 1 for c in scc(n, adj))


def classify_booleans(graph: DepGraph) -> Dict[str, bool]:
    """The four raw cycle predicates, from Tarjan/BFS on the host —
    the reference the device closure is differentially held to."""
    adj_ww = _adj(graph, (WW,))
    adj_wwwr = _adj(graph, (WW, WR))
    adj_full = _adj(graph, (WW, WR, RW))
    cyc_ww = _has_cycle(graph.n, adj_ww)
    cyc_wwwr = _has_cycle(graph.n, adj_wwwr)
    cyc_full = _has_cycle(graph.n, adj_full)
    gsingle = False
    if cyc_full:
        # a G-single cycle (one rw edge u->v + ww∪wr path v => u) lies
        # inside a full-graph SCC; search only there
        comp_of = {}
        for ci, comp in enumerate(scc(graph.n, adj_full)):
            if len(comp) > 1:
                for v in comp:
                    comp_of[v] = ci
        for u, v, t in zip(graph.src.tolist(), graph.dst.tolist(),
                           graph.et.tolist()):
            if t != RW:
                continue
            u, v = int(u), int(v)
            if comp_of.get(u) is None or comp_of.get(u) != comp_of.get(v):
                continue
            if _bfs_path(adj_wwwr, v, u) is not None:
                gsingle = True
                break
    return {"cyc_ww": cyc_ww, "cyc_wwwr": cyc_wwwr,
            "cyc_full": cyc_full, "gsingle": gsingle}


def derive_anomalies(b: Dict[str, bool]) -> List[str]:
    """Boolean predicates -> reported class list. Each class appears
    only when not implied by a stronger one, and the SAME derivation
    serves the device and host paths."""
    out: List[str] = []
    if b["cyc_ww"]:
        out.append("G0")
    if b["cyc_wwwr"] and not b["cyc_ww"]:
        out.append("G1c")
    if b["gsingle"] and not b["cyc_wwwr"]:
        out.append("G-single")
    if b["cyc_full"] and not (b["cyc_wwwr"] or b["gsingle"]):
        out.append("G2")
    return out


def _bfs_path(adj: List[List[Tuple[int, int]]], start: int,
              goal: int) -> Optional[List[int]]:
    """Shortest path start -> goal (deterministic: sorted adjacency,
    FIFO). Returns the node list including both ends, or None."""
    if start == goal:
        return [start]
    prev: Dict[int, int] = {start: -1}
    q: deque = deque([start])
    while q:
        u = q.popleft()
        for v, _t in adj[u]:
            if v in prev:
                continue
            prev[v] = u
            if v == goal:
                path = [v]
                while path[-1] != start:
                    path.append(prev[path[-1]])
                return list(reversed(path))
            q.append(v)
    return None


def _edge_type(graph_adj: List[List[Tuple[int, int]]], u: int,
               v: int) -> int:
    """The preferred (lowest-code: ww < wr < rw) edge type u -> v."""
    for dst, t in graph_adj[u]:          # sorted: (dst, et) ascending
        if dst == v:
            return t
    raise KeyError((u, v))


def find_witness(graph: DepGraph, cls: str) -> Optional[Dict[str, Any]]:
    """One concrete cycle of class ``cls``, deterministically (lowest
    node ids, shortest paths): ``{"cycle": [tid...], "edges":
    [type-name...]}`` where ``edges[i]`` labels ``cycle[i] ->
    cycle[i+1 mod len]``. None when the class has no cycle (callers
    only ask after a positive verdict)."""
    from jepsen_tpu.txn.infer import EDGE_NAMES

    types = _CLASS_EDGES.get(cls)
    if types is None:
        return None
    adj = _adj(graph, types)
    if cls == "G-single":
        adj_wwwr = _adj(graph, (WW, WR))
        # only rw edges inside a full-graph SCC can close a cycle:
        # filtering first keeps the witness walk O(core), not
        # O(rw-edges * E) over a 100k-txn graph
        comp_of: Dict[int, int] = {}
        for ci, comp in enumerate(scc(graph.n, adj)):
            if len(comp) > 1:
                for v in comp:
                    comp_of[v] = ci
        rw_edges = sorted(
            (int(u), int(v))
            for u, v, t in zip(graph.src.tolist(), graph.dst.tolist(),
                               graph.et.tolist())
            if t == RW and comp_of.get(int(u)) is not None
            and comp_of.get(int(u)) == comp_of.get(int(v)))
        for u, v in rw_edges:
            path = _bfs_path(adj_wwwr, v, u)
            if path is not None:
                cycle = [u] + path[:-1]
                edges = [RW] + [_edge_type(adj_wwwr, path[i],
                                           path[i + 1])
                                for i in range(len(path) - 1)]
                return {"cycle": cycle,
                        "edges": [EDGE_NAMES[t] for t in edges]}
        return None
    # G0 / G1c / G2: shortest cycle through the smallest node of the
    # first multi-node SCC of the restricted graph
    for comp in scc(graph.n, adj):
        if len(comp) < 2:
            continue
        start = comp[0]
        comp_set = set(comp)
        sub = [[(v, t) for v, t in adj[u] if v in comp_set]
               for u in range(graph.n)]
        for succ, _t in sub[start]:
            path = _bfs_path(sub, succ, start)
            if path is not None:
                cycle = [start] + path[:-1]
                edges = [_edge_type(sub, cycle[i],
                                    cycle[(i + 1) % len(cycle)])
                         for i in range(len(cycle))]
                return {"cycle": cycle,
                        "edges": [EDGE_NAMES[t] for t in edges]}
    return None


# -- consistency-lattice host reference (ISSUE 17) -----------------------
#
# The snapshot-isolation lane (ww ∪ wr ∪ cm) needs commit-order
# reachability WITHOUT materializing the dense [n, n] cm mask (the
# host reference must run on graphs far past the dense envelope). The
# chain-node trick realizes the interval order in O(n) extra nodes and
# edges: one chain node per txn in start order, forward chain edges,
# an entry edge into each txn from its start position, and one exit
# edge from each committed txn to the first chain position whose start
# follows its commit. Then u ⇒cm⇒ v iff a chain path u → … → v exists,
# and cm composed with dependency edges is plain reachability on the
# extended graph. Chain edges are labeled :data:`CM` so witness walks
# contract chain runs back into one reported ``cm`` hop.

_LANE_NAMES = ("ww", "wr", "rw", "cm")


def _chain_adj(graph: DepGraph, starts: np.ndarray, ends: np.ndarray,
               types: Sequence[int] = (WW, WR)
               ) -> List[List[Tuple[int, int]]]:
    """Extended adjacency (2n nodes: txns 0..n-1, chain n..2n-1 in
    start order) over ``types`` dependency edges plus the commit-order
    chain. Sorted per node for deterministic walks."""
    n = graph.n
    adj: List[List[Tuple[int, int]]] = [[] for _ in range(2 * n)]
    order = np.argsort(starts, kind="stable")
    sorted_starts = starts[order]
    for p in range(n):
        if p + 1 < n:
            adj[n + p].append((n + p + 1, CM))
        adj[n + p].append((int(order[p]), CM))
    exits = np.searchsorted(sorted_starts, ends, side="right")
    for u in range(n):
        if ends[u] >= 0 and exits[u] < n:
            adj[u].append((n + int(exits[u]), CM))
    tset = set(types)
    for u, v, t in zip(graph.src.tolist(), graph.dst.tolist(),
                       graph.et.tolist()):
        if t in tset:
            adj[int(u)].append((int(v), int(t)))
    for lst in adj:
        lst.sort()
    return adj


def _contract_chain(path: List[int], n: int,
                    adj: List[List[Tuple[int, int]]]
                    ) -> Tuple[List[int], List[str]]:
    """Collapse chain-node runs of an extended-graph walk into single
    ``cm`` hops between real txns. Returns (real nodes in walk order,
    labels between consecutive reals — direct dependency edges keep
    their type name, chain detours report as ``cm``)."""
    reals: List[int] = []
    labels: List[str] = []
    prev: Optional[int] = None
    pend_cm = False
    for v in path:
        if v >= n:
            pend_cm = True
            continue
        if prev is not None:
            labels.append("cm" if pend_cm
                          else _LANE_NAMES[_edge_type(adj, prev, v)])
        reals.append(v)
        prev = v
        pend_cm = False
    return reals, labels


def lattice_classify_booleans(graph: DepGraph, starts: np.ndarray,
                              ends: np.ndarray) -> Dict[str, bool]:
    """The two SI-lane predicates on the host — the reference the
    ``[K, Np, NW]`` lattice closure is differentially held to:
    ``cyc_si`` (a cycle in ``ww ∪ wr ∪ cm``) and ``gsib`` (an rw edge
    closing such a cycle — exactly one anti-dependency)."""
    n = graph.n
    adj_ext = _chain_adj(graph, starts, ends, (WW, WR))
    cyc_si = False
    for comp in scc(2 * n, adj_ext):
        if sum(1 for v in comp if v < n) >= 2:
            cyc_si = True
            break
    gsib = False
    adj_full_ext = _chain_adj(graph, starts, ends, (WW, WR, RW))
    comp_of: Dict[int, int] = {}
    for ci, comp in enumerate(scc(2 * n, adj_full_ext)):
        if len(comp) > 1:
            for v in comp:
                comp_of[v] = ci
    for u, v, t in zip(graph.src.tolist(), graph.dst.tolist(),
                       graph.et.tolist()):
        if t != RW:
            continue
        u, v = int(u), int(v)
        if comp_of.get(u) is None or comp_of.get(u) != comp_of.get(v):
            continue
        if _bfs_path(adj_ext, v, u) is not None:
            gsib = True
            break
    return {"cyc_si": cyc_si, "gsib": gsib}


def gsia_scan(graph: DepGraph, starts: np.ndarray,
              ends: np.ndarray) -> Optional[Dict[str, Any]]:
    """Adya's G-SIa interference witness, restricted to what intervals
    can PROVE: a ww/wr dependency ``u → v`` where ``v`` committed
    before ``u`` even began — ``v`` observed (or was overwritten by) a
    transaction from its future. Deliberately NOT the classic
    "no commit-before-start" form, which brands every overlapping-but-
    correct history invalid; this form never fires on a real system.
    Returns the first witness in sorted edge order, or None."""
    best: Optional[Tuple[int, int, int]] = None
    for u, v, t in zip(graph.src.tolist(), graph.dst.tolist(),
                       graph.et.tolist()):
        if t == RW:
            continue
        u, v = int(u), int(v)
        if ends[v] >= 0 and ends[v] < starts[u]:
            cand = (u, v, int(t))
            if best is None or cand < best:
                best = cand
    if best is None:
        return None
    u, v, t = best
    return {"cycle": [u, v], "edges": [_LANE_NAMES[t], "cm"]}


def find_lattice_witness(graph: DepGraph, cls: str,
                         starts: np.ndarray, ends: np.ndarray
                         ) -> Optional[Dict[str, Any]]:
    """One concrete SI-lane witness, deterministically — the lattice
    analogue of :func:`find_witness` for the classes the commit-order
    lane adds: ``G-SIa`` (a dependency edge contradicting commit
    order), ``G-SIb`` (one rw edge closing a ``ww ∪ wr ∪ cm`` cycle),
    ``G-SI`` (any other cycle in that lane). Chain-node runs report
    as single ``cm`` hops."""
    n = graph.n
    if cls == "G-SIa":
        return gsia_scan(graph, starts, ends)
    adj_ext = _chain_adj(graph, starts, ends, (WW, WR))
    if cls == "G-SIb":
        adj_full_ext = _chain_adj(graph, starts, ends, (WW, WR, RW))
        comp_of: Dict[int, int] = {}
        for ci, comp in enumerate(scc(2 * n, adj_full_ext)):
            if len(comp) > 1:
                for v in comp:
                    comp_of[v] = ci
        rw_edges = sorted(
            (int(u), int(v))
            for u, v, t in zip(graph.src.tolist(), graph.dst.tolist(),
                               graph.et.tolist())
            if t == RW and comp_of.get(int(u)) is not None
            and comp_of.get(int(u)) == comp_of.get(int(v)))
        for u, v in rw_edges:
            path = _bfs_path(adj_ext, v, u)
            if path is not None:
                reals, labels = _contract_chain(path, n, adj_ext)
                return {"cycle": [u] + reals[:-1],
                        "edges": ["rw"] + labels}
        return None
    if cls == "G-SI":
        for comp in scc(2 * n, adj_ext):
            reals = [v for v in comp if v < n]
            if len(reals) < 2:
                continue
            start = reals[0]
            comp_set = set(comp)
            sub = [[(v, t) for v, t in adj_ext[u] if v in comp_set]
                   for u in range(2 * n)]
            for succ, _t in sub[start]:
                path = _bfs_path(sub, succ, start)
                if path is not None:
                    reals_c, labels = _contract_chain(
                        [start] + path, n, sub)
                    return {"cycle": reals_c[:-1], "edges": labels}
        return None
    return None


def trim_core(graph: DepGraph
              ) -> Tuple[np.ndarray, DepGraph]:
    """Kahn-peel the acyclic fringe (queue-based, O(V+E)): repeatedly
    strip in-degree-0 nodes, then out-degree-0 nodes on the remainder.
    Every cycle of every edge-type restriction survives (a subgraph
    cycle is a full-graph cycle). Returns ``(core_node_ids, core
    subgraph relabeled dense)`` — the dense device closure runs on the
    core when the full graph is past its envelope."""
    n = graph.n
    src = graph.src.astype(np.int64)
    dst = graph.dst.astype(np.int64)
    alive = np.ones(n, bool)
    for direction in range(2):
        s, d = (src, dst) if direction == 0 else (dst, src)
        indeg = np.zeros(n, np.int64)
        np.add.at(indeg, d, alive[s] & alive[d])
        # adjacency (forward for this direction) for queue propagation
        order = np.argsort(s, kind="stable")
        s_sorted, d_sorted = s[order], d[order]
        starts = np.searchsorted(s_sorted, np.arange(n + 1))
        q = deque(np.nonzero(alive & (indeg == 0))[0].tolist())
        while q:
            u = q.popleft()
            if not alive[u]:
                continue
            alive[u] = False
            for i in range(starts[u], starts[u + 1]):
                v = int(d_sorted[i])
                if alive[v]:
                    indeg[v] -= 1
                    if indeg[v] == 0:
                        q.append(v)
    core = np.nonzero(alive)[0]
    relabel = -np.ones(n, np.int64)
    relabel[core] = np.arange(len(core))
    keep = alive[src] & alive[dst]
    from jepsen_tpu.checkers import transfer
    dt = transfer.idx_dtype(max(len(core), 1), count=False)
    sub = DepGraph(
        n=len(core),
        src=relabel[src[keep]].astype(dt),
        dst=relabel[dst[keep]].astype(dt),
        et=graph.et[keep],
        txns=tuple(graph.txns[int(i)] for i in core),
        direct=(), counters={})
    return core, sub
