"""``jepsen_tpu.txn`` — the Elle-style transactional checker (ISSUE 9
tentpole): serializability anomaly detection for list-append workloads
as dependency-cycle search over the inferred wr/ww/rw graph, run as
batched boolean matrix squaring on the MXU.

Pipeline (:func:`check_history`):

1. :mod:`.ops`     — pair invocations/completions, normalize micro-ops,
   int-pack the history (narrow ``transfer.idx_dtype`` tensors);
2. :mod:`.infer`   — per-key append-order recovery (Elle traceability)
   → COO ww/wr/rw edge tensor; ambiguity degrades to documented-weaker
   edges with ``txn.infer.*`` counters, never silently;
3. :mod:`.cycles`  — the device closure: edge-type-restricted boolean
   transitive closures under one jitted batched squaring ladder, with
   diagonal hits as the G0 / G1c / G-single / G2 verdicts; Kahn-trim
   to the cyclic core past the dense envelope, row-block mesh tiling
   with ``devices``;
4. :mod:`.host_ref`— the Tarjan/SCC reference behind the
   exactly-one-obs-fallback contract (stage ``txn-closure``), and the
   shared deterministic witness walk both paths report through.

``facade.auto_check_txn`` is the routed entry (standard selection
ledger); :class:`TxnChecker` is the ``facade.compose``-able checker;
the serve daemon dispatches ``txn-list-append`` groups through the
same chain.
"""
from __future__ import annotations

import logging
import time as _time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence

from jepsen_tpu import obs, util
from jepsen_tpu.op import Op
from jepsen_tpu.txn import cycles, host_ref, infer as infer_mod, \
    lattice, ops
from jepsen_tpu.txn.infer import DepGraph
from jepsen_tpu.txn.ops import ListAppend, list_append_model

log = logging.getLogger("jepsen.txn")

__all__ = ["check_history", "check_graph", "TxnChecker", "txn_checker",
           "ListAppend", "list_append_model", "ops", "cycles",
           "host_ref", "lattice", "DepGraph"]


def _witness_detail(graph: DepGraph,
                    w: Optional[Dict[str, Any]]) -> Optional[Dict]:
    if w is None:
        return None
    return {"cycle": [graph.txns[i].describe() for i in w["cycle"]],
            "edges": list(w["edges"])}


def check_graph(graph: DepGraph, *,
                devices: Optional[Sequence] = None,
                max_dense_txns: Optional[int] = None,
                force_host: bool = False) -> Dict[str, Any]:
    """Cycle-search an inferred dependency graph. Routes the device
    closure first (trimming to the cyclic core past the dense
    envelope); any device failure records exactly ONE ``txn-closure``
    obs fallback and re-runs on the host SCC reference with identical
    verdict semantics. Gate declines (opt-out env, core past the
    envelope) are recorded route decisions, not fallbacks."""
    res: Dict[str, Any] = {"txns": graph.n, "edges": graph.e,
                           "edge-counts": graph.edge_counts()}
    if graph.e == 0:
        res.update({"valid": True, "anomalies": [],
                    "engine": "txn-noedges"})
        obs.count("txn.closure.trivial")
        return res
    booleans: Optional[Dict[str, bool]] = None
    engine = "txn-host-scc"
    target = graph
    if force_host or not cycles.device_enabled():
        obs.decision("txn-closure", "route", cause="host-forced",
                     txns=graph.n, edges=graph.e)
    else:
        cap = max_dense_txns if max_dense_txns is not None \
            else cycles.max_dense()
        if not cycles.admits(graph.n, cap):
            # cycle-preserving Kahn trim: the dense closure only needs
            # the cyclic core (every class-restricted cycle survives)
            core_ids, core = host_ref.trim_core(graph)
            obs.count("txn.core.trimmed")
            obs.gauge("txn.core.n", int(core.n))
            res["core-txns"] = int(core.n)
            if cycles.admits(core.n, cap):
                target = core
            else:
                obs.decision("txn-closure", "route",
                             cause="core-overflow", txns=graph.n,
                             core=int(core.n))
                target = None
        if target is not None:
            try:
                booleans = cycles.closure_booleans(target,
                                                   devices=devices)
                engine = ("txn-mxu-tiled"
                          if devices is not None and len(devices) > 1
                          else "txn-mxu")
            except Exception as e:                      # noqa: BLE001
                log.warning("txn device closure failed (%r); host SCC "
                            "fallback", e, exc_info=e)
                obs.engine_fallback("txn-closure", type(e).__name__,
                                    txns=graph.n, edges=graph.e)
                booleans = None
    if booleans is None:
        booleans = host_ref.classify_booleans(graph)
        engine = "txn-host-scc"
        obs.count("txn.closure.host")
    anomalies = host_ref.derive_anomalies(booleans)
    res.update({"valid": not anomalies, "anomalies": anomalies,
                "engine": engine, "booleans": booleans})
    if anomalies:
        # witness extraction is host-side and shared by both engine
        # paths: walk one concrete cycle of the most severe class back
        # out of the FULL graph for the report
        res["anomaly"] = anomalies[0]
        res["witness"] = _witness_detail(
            graph, host_ref.find_witness(graph, anomalies[0]))
    return res


def check_history(history: Sequence[Op], *,
                  devices: Optional[Sequence] = None,
                  max_dense_txns: Optional[int] = None,
                  force_host: bool = False,
                  consistency: Optional[Any] = None) -> Dict[str, Any]:
    """The full transactional check: collect → infer → cycle-search.
    Inference-time (direct) anomalies — non-prefix reads, duplicate
    appends, G1a aborted reads — fail the history outright and skip
    the cycle stage (a poisoned order could fabricate cycles).

    With ``consistency`` (a lattice level name, a list of them, or
    ``"all"``) the check routes through the consistency lattice
    (:mod:`jepsen_tpu.txn.lattice`): the result carries per-level
    ``holds``/``levels``/``weakest-violated``, and ``valid`` gates on
    the REQUESTED level(s) — every level is evaluated either way,
    because one closure covers them all. ``consistency=None`` keeps
    the legacy serializable-only verdict bit-for-bit."""
    t0 = _time.monotonic()
    levels_req = (None if consistency is None
                  else lattice.canon_levels(consistency))
    # collect/infer allocate millions of long-lived micro-op tuples:
    # every gen0/1 collection re-scans the growing survivor set, so
    # GC is paused across the whole check (util.gc_paused — bounded,
    # re-entrant; the deferred collection runs at the caller's next
    # allocation). 100k rung: 2.6 -> 1.4 s host wall.
    with util.gc_paused():
        with obs.span("txn.collect"):
            txns, fails = ops.collect(history)
        with obs.span("txn.infer", txns=len(txns)):
            graph = infer_mod.infer(txns, fails)
        res: Dict[str, Any] = {}
        if graph.direct:
            kinds = sorted({d["type"] for d in graph.direct})
            res = {"valid": False, "txns": graph.n, "edges": graph.e,
                   "edge-counts": graph.edge_counts(),
                   "engine": "txn-infer",
                   "anomalies": kinds, "anomaly": kinds[0],
                   "direct": [dict(d) for d in graph.direct[:32]],
                   "direct-count": len(graph.direct)}
            if levels_req is not None:
                # direct anomalies poison EVERY lattice level
                res["consistency"] = list(levels_req)
                res["holds"] = lattice.all_false_holds()
                res["weakest-violated"] = lattice.LEVELS[0]
                res["levels"] = {
                    lvl: {"holds": False, "anomalies": kinds}
                    for lvl in lattice.LEVELS}
        elif levels_req is not None:
            with obs.span("txn.lattice", txns=graph.n, edges=graph.e):
                lat = lattice.check_levels(
                    graph, devices=devices,
                    max_dense_txns=max_dense_txns,
                    force_host=force_host)
            anomalies = [c for lvl in lattice.LEVELS
                         for c in lat["levels"][lvl]["anomalies"]]
            res = {"txns": graph.n, "edges": graph.e,
                   "edge-counts": graph.edge_counts(),
                   "valid": all(lat["holds"][lvl]
                                for lvl in levels_req),
                   "consistency": list(levels_req),
                   "holds": lat["holds"], "levels": lat["levels"],
                   "weakest-violated": lat["weakest-violated"],
                   "booleans": lat["booleans"],
                   "engine": lat["engine"],
                   "anomalies": anomalies}
            if lat["session-violations"]:
                res["session-violations"] = lat["session-violations"]
            if anomalies:
                res["anomaly"] = anomalies[0]
                wv = lat["weakest-violated"]
                w = lat["levels"][wv].get("witness") if wv else None
                if w is not None:
                    res["witness"] = (_witness_detail(graph, w)
                                      if "cycle" in w else w)
        else:
            with obs.span("txn.cycles", txns=graph.n, edges=graph.e):
                res = check_graph(graph, devices=devices,
                                  max_dense_txns=max_dense_txns,
                                  force_host=force_host)
    res["failed-txns"] = len(fails)
    res["infer"] = dict(graph.counters)
    if graph.counters.get("ambiguous_appends"):
        # weaker edges were inferred (unobserved appends have no
        # position): the verdict stands on what WAS observable
        res["coverage"] = "weakened"
    res["check-s"] = round(_time.monotonic() - t0, 6)
    return res


# keyword subset the facade filters per-request options down to
_TXN_KW = ("devices", "max_dense_txns", "force_host", "consistency")


@dataclass
class TxnChecker:
    """``facade.compose``-able transactional checker: Elle-style
    list-append serializability over the whole history (non-txn ops —
    nemesis, mixed workloads — are ignored by :func:`ops.collect`)."""
    opts: Dict[str, Any] = field(default_factory=dict)
    name = "txn"

    def check(self, test, history, opts=None):
        from jepsen_tpu.checkers import facade
        kw = dict(self.opts)
        if opts:
            kw.update(opts)
        return facade.auto_check_txn(history, kw)


def txn_checker(**opts: Any) -> TxnChecker:
    return TxnChecker(opts=opts)
