"""The device half of the transactional checker: dependency-cycle
search as batched boolean matrix squaring on the MXU.

The inferred COO edges become three dense adjacency masks — the
edge-type-restricted graphs of the anomaly taxonomy (``ww`` for G0,
``ww ∪ wr`` for G1c, the full graph) — stacked ``[3, Np, Np]`` and
closed in ONE jitted program by repeated boolean squaring
(Fischer–Meyer: ``C ← C ∨ C·C``, ``⌈log2 Np⌉`` times), the same
reachability-as-matmul shape the ``reach_*`` engines run. The batch
axis rides a single ``einsum('bij,bjk->bik')`` — the vmap'd squaring
ladder — so all three closures share every MXU dispatch. Diagonal
hits are the cycle verdicts; the G-single predicate is one more
matmul-shaped contraction (``diag(A_rw · (C_wwwr ∨ I))``).

Wire discipline (the transfer diet): adjacency crosses host→device
bit-packed 8-per-byte (:func:`transfer.pack_bool`) and unpacks
on-device where bandwidth is free; the verdict fetch is FOUR booleans
(lazy-verdict shape — witness extraction is host-side from the COO
graph, nothing big ever crosses back). ``transfer.count_put``
accounts the packed vs blanket-f32 bytes.

Geometry: ``Np`` pads to the next power of two (≥ 8) so a serving
daemon compiles log2-many closure programs, not one per graph size.
Graphs past the dense envelope (:func:`admits`) are first Kahn-trimmed
to their cyclic core (:func:`jepsen_tpu.txn.host_ref.trim_core` —
cycle-preserving, so verdicts are unchanged); a core still past the
envelope declines to the host SCC reference (a recorded route, not a
crash). With ``devices`` the closure tiles row-blocks over the 1-D
mesh from :mod:`jepsen_tpu.parallel` (each chip squares its block
against the all-gathered matrix), for graphs past one chip's HBM.
"""
from __future__ import annotations

import math
import os
from functools import lru_cache
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.txn.infer import CM, RW, WR, WW, DepGraph

# dense closure envelope: Np*Np f32 intermediates, 4 lanes — 8192 is
# ~1 GiB of HBM transients on one chip. Overridable for tests/bench.
_MAX_DENSE_DEFAULT = 8192


def max_dense() -> int:
    try:
        return int(os.environ.get("JEPSEN_TPU_TXN_MAX_DENSE", "") or
                   _MAX_DENSE_DEFAULT)
    # jtlint: ok fallback — malformed gate value falls back to the default cap
    except ValueError:
        return _MAX_DENSE_DEFAULT


def device_enabled() -> bool:
    """``JEPSEN_TPU_NO_TXN_DEVICE=1`` opts the closure kernel out
    (consulted per call, like the transfer-diet gates)."""
    return not os.environ.get("JEPSEN_TPU_NO_TXN_DEVICE")


def word_closure_enabled() -> bool:
    """The word-packed closure body (rows as uint32 bitmask words,
    the squaring ladder as AND + word-wise any/popcount) is the
    DEFAULT kernel body; ``JEPSEN_TPU_NO_WORD_CLOSURE=1`` opts back
    to the f32 einsum body — which is also the recorded fallback when
    the word body fails (consulted per call)."""
    return not os.environ.get("JEPSEN_TPU_NO_WORD_CLOSURE")


def _closure_body(Np: int) -> str:
    """Body selection: the persisted autotune table first (a
    ``closure`` winner recorded by ``tools/closure_sweep.py`` /
    ``bench.py``), then the word-packed default."""
    if not word_closure_enabled():
        return "f32"
    from jepsen_tpu.checkers import autotune
    w = autotune.winner("closure", autotune.closure_key(Np))
    if w in ("word", "f32"):
        return w
    return "word"


def admits(n: int, cap: Optional[int] = None) -> bool:
    return n <= (cap if cap is not None else max_dense())


def _pad_n(n: int) -> int:
    return max(8, 1 << max(0, (n - 1)).bit_length())


def _masks(graph: DepGraph, Np: int
           ) -> Tuple[np.ndarray, np.ndarray]:
    """COO -> stacked dense masks [3, Np, Np] (ww / ww∪wr / full) and
    the rw mask [Np, Np]."""
    masks = np.zeros((3, Np, Np), bool)
    rw = np.zeros((Np, Np), bool)
    src = graph.src.astype(np.int64)
    dst = graph.dst.astype(np.int64)
    et = graph.et
    ww_m = et == WW
    wr_m = et == WR
    rw_m = et == RW
    masks[0, src[ww_m], dst[ww_m]] = True
    masks[1][masks[0]] = True
    masks[1, src[wr_m], dst[wr_m]] = True
    masks[2][masks[1]] = True
    masks[2, src[rw_m], dst[rw_m]] = True
    rw[src[rw_m], dst[rw_m]] = True
    return masks, rw


@lru_cache(maxsize=32)
def _lattice_call(Np: int, K: int, contracts: Tuple[int, ...],
                  packed_wire: bool):
    """One compiled closure program per (padded geometry, lane count,
    contraction set, wire format): unpack-on-device, the batched
    squaring ladder over ``K`` stacked lane masks, diagonal reduction,
    and one rw contraction per lane in ``contracts`` — verdict is
    ``K + len(contracts)`` bools. The legacy serializable closure is
    the ``K=3, contracts=(1,)`` instance; the consistency lattice adds
    the ``ww ∪ wr ∪ cm`` lane and its G-SIb contraction — same ladder,
    one more batch row."""
    import jax
    import jax.numpy as jnp

    n_iter = max(1, math.ceil(math.log2(Np)))

    def fn(wireK, wire_rw):
        if packed_wire:
            A = jnp.unpackbits(wireK, count=K * Np * Np) \
                   .reshape(K, Np, Np).astype(jnp.float32)
            Arw = jnp.unpackbits(wire_rw, count=Np * Np) \
                     .reshape(Np, Np).astype(jnp.float32)
        else:
            A = wireK.astype(jnp.float32)
            Arw = wire_rw.astype(jnp.float32)
        C = A
        for _ in range(n_iter):
            # entries stay exactly {0,1}: path counts are re-saturated
            # every squaring, so f32 never overflows (max count <= Np)
            prod = jnp.einsum("bij,bjk->bik", C, C,
                              preferred_element_type=jnp.float32)
            C = jnp.where(prod > 0, 1.0, C)
        cyc = jnp.einsum("bii->b", C) > 0                    # [K]
        eye = jnp.eye(Np, dtype=jnp.float32)
        gs = [jnp.einsum("ij,ji->", Arw,
                         jnp.maximum(C[L], eye))[None] > 0
              for L in contracts]
        return jnp.concatenate([cyc] + gs)

    return jax.jit(fn)


def _closure_call(Np: int, packed_wire: bool):
    """The legacy 4-boolean serializable closure program — the
    ``K=3, contracts=(1,)`` lattice instance (bit-identical outputs:
    ``[cyc_ww, cyc_wwwr, cyc_full, gsingle]``)."""
    return _lattice_call(Np, 3, (1,), packed_wire)


# -- word-packed closure body (the bit-parallel default) -----------------
#
# Four-Russians-style boolean matrix multiplication: each adjacency /
# closure row lives as ceil(Np/32) uint32 words (bit ``k & 31`` of
# word ``k >> 5`` = edge i -> k), 32x denser than the f32 masks. One
# squaring step computes ``prod[b, i, k] = OR_j C[b,i,j] & C[b,j,k]``
# as a word-wise AND between row-packed C and TRANSPOSE-packed C
# reduced over the word axis (``any(words != 0)`` — the popcount>0
# predicate without paying the count), so each multiply-accumulate of
# the f32 einsum becomes one AND over 32 matrix entries. Both
# packings are maintained (row- and transpose-packed) so no device
# transpose is ever paid; the G-single contraction collapses to ONE
# [Np, NW] AND (``any(Arw_w & reflT_w)``). Verdicts are bit-identical
# to the f32 ladder and the host SCC (differentially tested); the
# f32 body stays as the recorded fallback (`word-closure` obs stage)
# and the ``JEPSEN_TPU_NO_WORD_CLOSURE=1`` opt-out.

_WORD_NP_FLOOR = 32                      # words pack 32 columns


def _pad_n_words(n: int) -> int:
    return max(_WORD_NP_FLOOR, _pad_n(n))


def _pack_rows(a: np.ndarray) -> np.ndarray:
    """bool [..., K] (K % 32 == 0) -> uint32 [..., K/32], bit
    ``k & 31`` of word ``k >> 5`` = a[..., k]."""
    p = np.packbits(np.ascontiguousarray(a, np.uint8), axis=-1,
                    bitorder="little")
    return np.ascontiguousarray(p).view(np.uint32) \
        .reshape(a.shape[:-1] + (a.shape[-1] // 32,))


@lru_cache(maxsize=32)
def _lattice_word_call(Np: int, K: int, contracts: Tuple[int, ...]):
    """One compiled word-packed closure program per (padded geometry,
    lane count, contraction set): operands are the row-packed and
    transpose-packed adjacency words (host-packed — 32x fewer wire
    bytes than even uint8) and the row-packed rw mask; verdict is
    ``K + len(contracts)`` bools. The legacy serializable closure is
    the ``K=3, contracts=(1,)`` instance."""
    import jax
    import jax.numpy as jnp

    NW = Np // 32
    n_iter = max(1, math.ceil(math.log2(Np)))
    pw = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))

    def pack_last(dense_bool):
        """bool [..., Np] -> uint32 [..., NW] (sum of distinct bits
        == OR; no carries)."""
        x = dense_bool.reshape(dense_bool.shape[:-1] + (NW, 32)) \
            .astype(jnp.uint32)
        return (x * pw).sum(-1).astype(jnp.uint32)

    def fn(Cw, CwT, Arw_w):
        for _ in range(n_iter):
            # prod[b, i, k] = any_w (Cw[b,i,w] & CwT[b,k,w]) — the
            # AND+popcount boolean matmul, fused by XLA into one
            # reduction loop (no [Np, Np, NW] materialization)
            prod = jnp.any(
                (Cw[:, :, None, :] & CwT[:, None, :, :]) != 0,
                axis=-1)
            Cw = Cw | pack_last(prod)
            CwT = CwT | pack_last(jnp.swapaxes(prod, 1, 2))
        i = jnp.arange(Np)
        dwords = Cw[:, i, i >> 5]                        # [K, Np]
        cyc = (((dwords >> (i & 31).astype(jnp.uint32)) & 1) > 0) \
            .any(axis=1)
        eye_w = ((jnp.arange(NW)[None, :] == (i >> 5)[:, None])
                 .astype(jnp.uint32)
                 * (jnp.uint32(1) << (i & 31).astype(jnp.uint32)
                    )[:, None])                          # [Np, NW]
        gs = [jnp.any((Arw_w & (CwT[L] | eye_w)) != 0)[None]
              for L in contracts]
        return jnp.concatenate([cyc] + gs)

    return jax.jit(fn)


def _closure_word_call(Np: int):
    """The legacy 4-boolean word-packed closure program — the
    ``K=3, contracts=(1,)`` lattice instance."""
    return _lattice_word_call(Np, 3, (1,))


def _word_closure_booleans(masks: np.ndarray, rw: np.ndarray,
                           Np: int,
                           contracts: Tuple[int, ...] = (1,)
                           ) -> np.ndarray:
    """Run the word-packed one-shot closure. ``masks``/``rw`` are the
    dense [K, Np, Np]/[Np, Np] bool masks; re-pads to the word floor
    (words pack 32 columns) before packing. Callers bump their own
    body counter (``txn.closure.word`` / ``txn.lattice.word``) so the
    literals stay visible to the counter-drift lint."""
    from jepsen_tpu.checkers import transfer

    K = masks.shape[0]
    Npw = _pad_n_words(Np)
    if Npw != masks.shape[1]:
        grown = np.zeros((K, Npw, Npw), bool)
        grown[:, :masks.shape[1], :masks.shape[2]] = masks
        masks = grown
        grown_rw = np.zeros((Npw, Npw), bool)
        grown_rw[:rw.shape[0], :rw.shape[1]] = rw
        rw = grown_rw
    Cw = _pack_rows(masks)
    CwT = _pack_rows(np.swapaxes(masks, 1, 2))
    Arw_w = _pack_rows(rw)
    transfer.count_put(
        int(Cw.nbytes + CwT.nbytes + Arw_w.nbytes),
        int((masks.size + rw.size) * 4))
    return np.asarray(_lattice_word_call(Npw, K, contracts)(
        Cw, CwT, Arw_w))


def _put_wire(masks: np.ndarray, rw: np.ndarray
              ) -> Tuple[Any, Any, bool]:
    """Marshal the adjacency under the diet: bit-packed 8-per-byte
    when the packed-wire gate is open, dense uint8 otherwise; bytes
    accounted either way against the blanket f32 reference."""
    from jepsen_tpu.checkers import transfer

    packed_wire = transfer.packed_enabled()
    if packed_wire:
        w3 = transfer.pack_bool(masks)
        wrw = transfer.pack_bool(rw)
    else:
        w3 = masks.astype(np.uint8)
        wrw = rw.astype(np.uint8)
    transfer.count_put(int(w3.nbytes + wrw.nbytes),
                       int((masks.size + rw.size) * 4))
    return w3, wrw, packed_wire


def closure_booleans(graph: DepGraph,
                     devices: Optional[Sequence] = None
                     ) -> Dict[str, bool]:
    """The four cycle predicates from the device closure. Raises on
    any device failure — the caller owns the exactly-one-obs-fallback
    contract to the host SCC reference."""
    Np = _pad_n(graph.n)
    masks, rw = _masks(graph, Np)
    if devices is not None and len(devices) > 1:
        out = _tiled_booleans(masks, rw, Np, list(devices))
    elif _closure_body(Np) == "word":
        try:
            out = _word_closure_booleans(masks, rw, Np)
            obs.count("txn.closure.word")
        except Exception as e:                          # noqa: BLE001
            # the f32 einsum body is the RECORDED fallback of the
            # word-packed default: exactly one obs record, then the
            # round-8 dispatch — a further failure raises to the
            # caller's host-SCC ladder as before
            obs.engine_fallback("word-closure", type(e).__name__,
                                txns=graph.n, edges=graph.e)
            w3, wrw, packed_wire = _put_wire(masks, rw)
            out = np.asarray(_closure_call(Np, packed_wire)(w3, wrw))
            obs.count("txn.closure.device")
    else:
        w3, wrw, packed_wire = _put_wire(masks, rw)
        out = np.asarray(_closure_call(Np, packed_wire)(w3, wrw))
        obs.count("txn.closure.device")
    return {"cyc_ww": bool(out[0]), "cyc_wwwr": bool(out[1]),
            "cyc_full": bool(out[2]), "gsingle": bool(out[3])}


# -- consistency-lattice closure (ISSUE 17) ------------------------------

# lattice lane stack: 0 = ww, 1 = ww∪wr, 2 = ww∪wr∪rw (full),
# 3 = ww∪wr∪cm (the SI start/commit lane); contractions on lane 1
# (G-single) and lane 3 (G-SIb: an rw edge closing a commit-order
# cycle — write skew between non-overlapping txns)
LATTICE_K = 4
LATTICE_CONTRACTS = (1, 3)
LATTICE_KEYS = ("cyc_ww", "cyc_wwwr", "cyc_full", "cyc_si",
                "gsingle", "gsib")


def _lattice_masks(graph: DepGraph, Np: int, cm: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """COO + commit mask -> stacked dense lane masks [4, Np, Np]
    (ww / ww∪wr / full / ww∪wr∪cm) and the rw mask [Np, Np]."""
    masks3, rw = _masks(graph, Np)
    masks = np.zeros((LATTICE_K, Np, Np), bool)
    masks[:3] = masks3
    masks[3] = masks3[1]
    masks[3, :cm.shape[0], :cm.shape[1]] |= cm
    return masks, rw


def lattice_booleans(graph: DepGraph, cm: np.ndarray,
                     devices: Optional[Sequence] = None
                     ) -> Dict[str, bool]:
    """The six lattice cycle predicates from ONE device closure — the
    ``[K, Np, NW]`` generalization of :func:`closure_booleans`:
    checking every consistency level costs one squaring ladder, not
    five. Raises on any device failure — the caller owns the
    exactly-one-obs-fallback contract to the host lattice reference.

    The lattice ladder is single-chip only (the row-block mesh tiling
    stays a serializable-closure specialization): a multi-device
    request runs the same single-chip program, recorded as a route
    decision, never silently."""
    Np = _pad_n(graph.n)
    masks, rw = _lattice_masks(graph, Np, cm)
    if devices is not None and len(devices) > 1:
        obs.decision("txn-lattice", "route", cause="single-chip",
                     devices=len(devices), txns=graph.n)
        obs.count("txn.lattice.single_chip_route")
    if _closure_body(Np) == "word":
        try:
            out = _word_closure_booleans(
                masks, rw, Np, contracts=LATTICE_CONTRACTS)
            obs.count("txn.lattice.word")
        except Exception as e:                          # noqa: BLE001
            # the f32 einsum body is the RECORDED fallback of the
            # word-packed default, exactly as on the serializable path
            obs.engine_fallback("word-closure", type(e).__name__,
                                txns=graph.n, edges=graph.e)
            w3, wrw, packed_wire = _put_wire(masks, rw)
            out = np.asarray(_lattice_call(
                Np, LATTICE_K, LATTICE_CONTRACTS, packed_wire)(
                w3, wrw))
            obs.count("txn.lattice.device")
    else:
        w3, wrw, packed_wire = _put_wire(masks, rw)
        out = np.asarray(_lattice_call(
            Np, LATTICE_K, LATTICE_CONTRACTS, packed_wire)(w3, wrw))
        obs.count("txn.lattice.device")
    return {k: bool(out[i]) for i, k in enumerate(LATTICE_KEYS)}


# -- incremental closure (streaming check sessions) ----------------------
#
# A txn session maintains the closed reachability masks C [3, Np, Np]
# DEVICE-RESIDENT across appends and re-closes only the dirty
# row/column blocks per append batch: every path a new edge enables
# decomposes as  old-reach → (junction path within the dirty node set
# D) → old-reach,  because each new edge's endpoints are in D and C
# was already transitively closed. So one append costs
#
#   1. scatter the new edges into C / A_rw (in place, donated);
#   2. close H = C1[D, D] — a [|D|, |D|] squaring ladder, log2(|D|)
#      iterations over the DIRTY block only;
#   3. C' = C1 ∨ (C1∨I)[:, D] · H* · (C1∨I)[D, :] — two skinny
#      [Np, d] matmuls instead of the full [Np, Np] ladder;
#   4. the same 4-boolean verdict fetch as the one-shot closure.
#
# Geometry: Np pads to powers of two and regrows by re-embedding the
# fetched masks (log2-many regrowths per session); |D| and the edge
# count pad to powers of two so a session compiles a bounded family
# of update programs.


class ClosureOverflow(RuntimeError):
    """The session's graph outgrew the dense closure envelope; the
    caller routes per-append verdicts to the host SCC reference."""


@lru_cache(maxsize=32)
def _inc_call(Np: int, d_pad: int, e_pad: int, K: int = 3,
              contracts: Tuple[int, ...] = (1,)):
    """One compiled dirty-block update per (geometry, dirty width,
    edge width, lane stack): scatter → dirty-block ladder → skinny
    closure join → verdict. The carried masks are donated (in-place
    advance). ``K=3, contracts=(1,)`` is the legacy serializable
    session; the lattice session carries the fourth (``ww∪wr∪cm``)
    lane and its G-SIb contraction through the same decomposition."""
    import jax
    import jax.numpy as jnp

    n_iter = max(1, math.ceil(math.log2(max(d_pad, 2))))

    def fn(C, Arw, esrc, edst, elane, erw, dsel):
        s = jnp.where(esrc < 0, 0, esrc)
        d = jnp.where(edst < 0, 0, edst)
        # scatter the batch's edges into the K lane masks + rw
        # (pad entries carry zero weight: .max(0) is the identity)
        for lane in range(K):
            C = C.at[lane, s, d].max(elane[lane])
        Arw = Arw.at[s, d].max(erw)
        dd = jnp.where(dsel < 0, 0, dsel)
        valid = (dsel >= 0).astype(jnp.float32)
        # dirty-block closure: junction paths between new-edge
        # endpoints, with old C entries as the long-range hops
        H = C[:, dd][:, :, dd] * valid[None, :, None] \
            * valid[None, None, :]
        for _ in range(n_iter):
            prod = jnp.einsum("bij,bjk->bik", H, H,
                              preferred_element_type=jnp.float32)
            H = jnp.where(prod > 0, 1.0, H)
        eyeD = (jnp.arange(Np)[:, None] == dd[None, :]) \
            .astype(jnp.float32) * valid[None, :]
        left = jnp.maximum(C[:, :, dd] * valid[None, None, :],
                           eyeD[None])
        right = jnp.maximum(C[:, dd, :] * valid[None, :, None],
                            eyeD.T[None])
        thru = jnp.einsum("bik,bkl->bil", left, H,
                          preferred_element_type=jnp.float32)
        add = jnp.einsum("bil,blj->bij", thru, right,
                         preferred_element_type=jnp.float32)
        C = jnp.where(add > 0, 1.0, C)
        cyc = jnp.einsum("bii->b", C) > 0
        eye = jnp.eye(Np, dtype=jnp.float32)
        gs = [jnp.einsum("ij,ji->", Arw,
                         jnp.maximum(C[L], eye))[None] > 0
              for L in contracts]
        return C, Arw, jnp.concatenate([cyc] + gs)

    return jax.jit(fn, donate_argnums=(0, 1))


def _pow2_at_least(n: int, floor: int = 8) -> int:
    return max(floor, 1 << max(0, (n - 1)).bit_length())


@lru_cache(maxsize=32)
def _inc_word_call(Np: int, d_pad: int, e_pad: int, K: int = 3,
                   contracts: Tuple[int, ...] = (1,)):
    """Word-packed dirty-block update: the carried closure lives as
    row-packed ``Cw`` + transpose-packed ``CwT`` [3, Np, NW] uint32
    (+ ``Arw_w`` [Np, NW]) — 32x denser device residency than the f32
    masks — and one append batch costs the same decomposition as the
    f32 body (scatter -> [d, d] junction ladder -> two skinny joins),
    with the scatter as 32 static bit-plane OR-scatters (per plane
    all values share one bit, so ``.max`` IS bitwise-or) and the
    join's [Np, Np] product never materialized: the add re-packs
    through fused OR-reductions against the packed right rows. The
    carried words are donated (in-place advance)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    NW = Np // 32
    n_iter = max(1, math.ceil(math.log2(max(d_pad, 2))))
    pw = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    zero32 = np.zeros((), np.uint32)[()]

    def pack_last(dense_bool):
        x = dense_bool.reshape(dense_bool.shape[:-1] + (NW, 32)) \
            .astype(jnp.uint32)
        return (x * pw).sum(-1).astype(jnp.uint32)

    def unpack_last(words):
        b = (words[..., :, None]
             >> jnp.arange(32, dtype=jnp.uint32)) & 1
        return b.reshape(words.shape[:-1] + (Np,)) != 0

    def _scatter_bits(dst_words, rows, cols, vals01):
        """OR ``vals01`` (0/1 per entry, leading lane axes allowed)
        as bit ``cols & 31`` into ``dst_words[..., rows, cols >> 5]``
        — 32 static bit-plane passes. WITHIN a pass every nonzero
        value carries the same single bit, so scatter-``max`` into a
        zero scratch IS bitwise-or even under duplicate (row, word)
        slots; ACROSS passes the scratches combine with ``|`` (a
        direct ``.at[].max`` on the accumulator would clobber
        previously set different bits: max(1, 8) = 8)."""
        cw = cols >> 5
        cb = (cols & 31).astype(jnp.uint32)
        for t in range(32):
            val = jnp.where(cb == t, jnp.uint32(1) << t, zero32)
            add = jnp.zeros_like(dst_words).at[..., rows, cw].max(
                vals01.astype(jnp.uint32) * val)
            dst_words = dst_words | add
        return dst_words

    def fn(Cw, CwT, Arw_w, esrc, edst, elane, erw, dsel):
        s = jnp.where(esrc < 0, 0, esrc)
        d = jnp.where(edst < 0, 0, edst)
        el = elane != 0                                   # [3, e_pad]
        Cw = _scatter_bits(Cw, s, d, el)
        CwT = _scatter_bits(CwT, d, s, el)
        Arw_w = _scatter_bits(Arw_w, s, d, erw != 0)
        dd = jnp.where(dsel < 0, 0, dsel)
        valid = (dsel >= 0).astype(jnp.float32)
        dw = dd >> 5
        db = (dd & 31).astype(jnp.uint32)
        # dirty-block extraction from the packed rows: H[b, j, k] =
        # bit dd[k] of row dd[j]
        rows_w = Cw[:, dd, :] * (valid.astype(jnp.uint32)
                                 )[None, :, None]         # [3, d, NW]
        Hw = rows_w[:, :, dw]                             # [3, d, d]
        H = (((Hw >> db[None, None, :]) & 1).astype(jnp.float32)
             * valid[None, :, None] * valid[None, None, :])
        for _ in range(n_iter):
            prod = jnp.einsum("bij,bjk->bik", H, H,
                              preferred_element_type=jnp.float32)
            H = jnp.where(prod > 0, 1.0, H)
        # left = (C ∨ I)[:, dd] dense skinny [3, Np, d]
        eyeD = (jnp.arange(Np)[:, None] == dd[None, :]) \
            .astype(jnp.float32) * valid[None, :]
        colw = Cw[:, :, dw]                               # [3, Np, d]
        left = jnp.maximum(
            ((colw >> db[None, None, :]) & 1).astype(jnp.float32)
            * valid[None, None, :], eyeD[None])
        thru = jnp.einsum("bik,bkl->bil", left, H,
                          preferred_element_type=jnp.float32)
        # right = (C ∨ I)[dd, :] kept PACKED: the [Np, Np] add image
        # re-packs through a fused OR-reduce instead of a dense f32
        # product
        eyeD_w = pack_last(eyeD.T)                        # [d, NW]
        right_w = rows_w | eyeD_w[None]                   # [3, d, NW]
        m = thru > 0                                      # [3, Np, d]
        add_w = lax.reduce(
            jnp.where(m[:, :, :, None], right_w[:, None, :, :],
                      zero32),
            zero32, lax.bitwise_or, (2,))                 # [3, Np, NW]
        Cw = Cw | add_w
        # transpose-packed update: addT[b, j, i] = OR_k right[b,k,j]
        # & thru[b,i,k] — pack thru over i, mask by the dense right
        right_dense = unpack_last(right_w)                # [3, d, Np]
        thruT_w = pack_last(jnp.swapaxes(m, 1, 2))        # [3, d, NW]
        addT_w = lax.reduce(
            jnp.where(right_dense[:, :, :, None],
                      thruT_w[:, :, None, :], zero32),
            zero32, lax.bitwise_or, (1,))                 # [3, Np, NW]
        CwT = CwT | addT_w
        i = jnp.arange(Np)
        dwords = Cw[:, i, i >> 5]
        cyc = (((dwords >> (i & 31).astype(jnp.uint32)) & 1) > 0) \
            .any(axis=1)
        eye_w = ((jnp.arange(NW)[None, :] == (i >> 5)[:, None])
                 .astype(jnp.uint32)
                 * (jnp.uint32(1) << (i & 31).astype(jnp.uint32)
                    )[:, None])
        gs = [jnp.any((Arw_w & (CwT[L] | eye_w)) != 0)[None]
              for L in contracts]
        return Cw, CwT, Arw_w, jnp.concatenate([cyc] + gs)

    return jax.jit(fn, donate_argnums=(0, 1, 2))


class IncrementalClosure:
    """Device-resident incremental transitive closure for one txn
    session. ``add_block(n_txns, src, dst, et)`` folds an append
    batch's new edges in and returns the four cycle booleans (the
    same :func:`closure_booleans` keys) — six with ``lattice=True``,
    where the carried stack grows the ``ww∪wr∪cm`` lane, ``et`` may
    carry :data:`~jepsen_tpu.txn.infer.CM` rows, and the verdict adds
    ``cyc_si``/``gsib`` (:data:`LATTICE_KEYS`). Raises
    :class:`ClosureOverflow` when the graph outgrows the dense
    envelope and any device failure to the caller, which owns the
    exactly-one-obs-fallback contract."""

    def __init__(self, *, max_dense_txns: Optional[int] = None,
                 lattice: bool = False) -> None:
        self._cap = (max_dense_txns if max_dense_txns is not None
                     else max_dense())
        self.Np = 0
        self.lattice = lattice
        self.K = LATTICE_K if lattice else 3
        self._contracts = LATTICE_CONTRACTS if lattice else (1,)
        # body pinned at construction (a session must not flip bodies
        # mid-stream — the carried state formats differ)
        self.packed = _closure_body(_WORD_NP_FLOOR) == "word"
        self._C = None                      # f32 [K, Np, Np] on device
        self._Arw = None                    # f32 [Np, Np] on device
        self._Cw = None                     # u32 [K, Np, NW] (packed)
        self._CwT = None                    # u32 [K, Np, NW] (packed)
        self._Arw_w = None                  # u32 [Np, NW]    (packed)
        self.updates = 0

    def _seed(self, Np: int) -> None:
        import jax
        import jax.numpy as jnp
        self.Np = Np
        if self.packed:
            NW = Np // 32
            self._Cw = jax.device_put(
                jnp.zeros((self.K, Np, NW), jnp.uint32))
            self._CwT = jax.device_put(
                jnp.zeros((self.K, Np, NW), jnp.uint32))
            self._Arw_w = jax.device_put(
                jnp.zeros((Np, NW), jnp.uint32))
            return
        self._C = jax.device_put(
            jnp.zeros((self.K, Np, Np), jnp.float32))
        self._Arw = jax.device_put(jnp.zeros((Np, Np), jnp.float32))

    def _regrow(self, n: int) -> None:
        """Re-embed the carried masks into the next power-of-two
        geometry (closure is preserved: new nodes have no edges). The
        packed re-embed copies WORDS: the old Np is a multiple of 32,
        so old columns occupy whole words of the new layout."""
        Np2 = _pad_n_words(n) if self.packed else _pad_n(n)
        if n > self._cap:
            raise ClosureOverflow(
                f"session graph {n} txns > dense cap {self._cap}")
        if self.P_empty:
            self._seed(Np2)
            return
        import jax
        from jepsen_tpu.checkers import transfer
        if self.packed:
            NW2 = Np2 // 32
            Cw = np.asarray(self._Cw)
            CwT = np.asarray(self._CwT)
            Aw = np.asarray(self._Arw_w)
            NW = Cw.shape[2]
            Cw2 = np.zeros((self.K, Np2, NW2), np.uint32)
            CwT2 = np.zeros((self.K, Np2, NW2), np.uint32)
            Aw2 = np.zeros((Np2, NW2), np.uint32)
            Cw2[:, :self.Np, :NW] = Cw
            CwT2[:, :self.Np, :NW] = CwT
            Aw2[:self.Np, :NW] = Aw
            transfer.count_put(
                int(Cw2.nbytes + CwT2.nbytes + Aw2.nbytes),
                int((2 * self.K + 1) * Np2 * Np2 * 4))
            self.Np = Np2
            self._Cw = jax.device_put(Cw2)
            self._CwT = jax.device_put(CwT2)
            self._Arw_w = jax.device_put(Aw2)
            obs.count("txn.closure.regrow")
            return
        C = np.asarray(self._C)
        Arw = np.asarray(self._Arw)
        C2 = np.zeros((self.K, Np2, Np2), np.float32)
        Arw2 = np.zeros((Np2, Np2), np.float32)
        C2[:, :self.Np, :self.Np] = C
        Arw2[:self.Np, :self.Np] = Arw
        transfer.count_put(int(C2.nbytes + Arw2.nbytes),
                           int(C2.nbytes + Arw2.nbytes))
        self.Np = Np2
        self._C = jax.device_put(C2)
        self._Arw = jax.device_put(Arw2)
        obs.count("txn.closure.regrow")

    @property
    def P_empty(self) -> bool:
        return (self._Cw is None) if self.packed else (self._C is None)

    def add_block(self, n_txns: int, src: np.ndarray, dst: np.ndarray,
                  et: np.ndarray) -> Dict[str, bool]:
        """Fold one append batch's new edges into the carried closure
        and return the cycle booleans."""
        import jax.numpy as jnp

        if n_txns > self._cap:
            raise ClosureOverflow(
                f"session graph {n_txns} txns > dense cap {self._cap}")
        if self.P_empty or n_txns > self.Np:
            self._regrow(max(n_txns, 1))
        e = len(src)
        e_pad = _pow2_at_least(max(e, 1))
        d_ids = np.unique(np.concatenate([src, dst])) if e else \
            np.zeros(0, np.int64)
        d_pad = min(self.Np, _pow2_at_least(max(len(d_ids), 1)))
        if len(d_ids) > d_pad:              # cannot happen: |D| <= Np
            d_pad = _pow2_at_least(len(d_ids))
        esrc = np.full(e_pad, -1, np.int32)
        edst = np.full(e_pad, -1, np.int32)
        esrc[:e] = src
        edst[:e] = dst
        elane = np.zeros((self.K, e_pad), np.float32)
        erw = np.zeros(e_pad, np.float32)
        from jepsen_tpu.txn.infer import CM, RW, WR, WW
        elane[0, :e] = (et == WW)
        elane[1, :e] = (et == WW) | (et == WR)
        if self.lattice:
            # lane 2 (full) excludes the commit-order rows; lane 3 is
            # the SI lane: ww ∪ wr ∪ cm
            elane[2, :e] = (et != CM)
            elane[3, :e] = (et == WW) | (et == WR) | (et == CM)
        else:
            elane[2, :e] = 1.0
        erw[:e] = (et == RW)
        dsel = np.full(d_pad, -1, np.int32)
        dsel[:len(d_ids)] = d_ids
        from jepsen_tpu.checkers import transfer
        wire = int(esrc.nbytes + edst.nbytes + elane.nbytes
                   + erw.nbytes + dsel.nbytes)
        transfer.count_put(wire, wire)
        if self.packed:
            self._Cw, self._CwT, self._Arw_w, out = _inc_word_call(
                self.Np, d_pad, e_pad, self.K, self._contracts)(
                self._Cw, self._CwT, self._Arw_w, jnp.asarray(esrc),
                jnp.asarray(edst), jnp.asarray(elane),
                jnp.asarray(erw), jnp.asarray(dsel))
            self.updates += 1
            obs.count("txn.closure.incremental")
            obs.count("txn.closure.incremental_word")
            return self._bools(np.asarray(out))
        self._C, self._Arw, out = _inc_call(
            self.Np, d_pad, e_pad, self.K, self._contracts)(
            self._C, self._Arw, jnp.asarray(esrc), jnp.asarray(edst),
            jnp.asarray(elane), jnp.asarray(erw), jnp.asarray(dsel))
        self.updates += 1
        obs.count("txn.closure.incremental")
        return self._bools(np.asarray(out))

    def _bools(self, o: np.ndarray) -> Dict[str, bool]:
        if self.lattice:
            obs.count("txn.lattice.incremental")
            return {k: bool(o[i]) for i, k in enumerate(LATTICE_KEYS)}
        return {"cyc_ww": bool(o[0]), "cyc_wwwr": bool(o[1]),
                "cyc_full": bool(o[2]), "gsingle": bool(o[3])}


# -- mesh tiling ---------------------------------------------------------

@lru_cache(maxsize=16)
def _tiled_calls(Np: int, n_dev: int, dev_key: Any):
    """Compiled row-block step/verdict programs for one (geometry,
    mesh) pair: each device squares its [Np/n_dev, Np] block against
    the all-gathered matrix (the closure FLOPs shard n_dev ways; the
    gather is the transient the docs call out)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from jepsen_tpu import parallel

    devs = list(dev_key)
    m = parallel.mesh("shard", devs)
    rows = Np // n_dev
    row_sh = NamedSharding(m, P("shard", None))

    def step(Mb):
        M = jax.lax.all_gather(Mb, "shard", axis=0, tiled=True)
        prod = jnp.dot(Mb, M, preferred_element_type=jnp.float32)
        return jnp.where(prod > 0, 1.0, Mb)

    def diag_any(Mb):
        i0 = jax.lax.axis_index("shard") * rows
        d = Mb[jnp.arange(rows), i0 + jnp.arange(rows)]
        return jnp.any(d > 0)[None]

    def gsingle(Arw_b, C_b):
        Cg = jax.lax.all_gather(C_b, "shard", axis=0, tiled=True)
        i0 = jax.lax.axis_index("shard") * rows
        col = jax.lax.dynamic_slice_in_dim(Cg, i0, rows, axis=1)
        eye = (jnp.arange(Np)[:, None]
               == (i0 + jnp.arange(rows))[None, :]).astype(jnp.float32)
        refl = jnp.maximum(col, eye)                     # [Np, rows]
        vals = jnp.einsum("ij,ji->i", Arw_b, refl)
        return jnp.any(vals > 0)[None]

    sm = parallel.shard_map
    step_f = jax.jit(sm(step, m, in_specs=P("shard", None),
                        out_specs=P("shard", None), check=False))
    diag_f = jax.jit(sm(diag_any, m, in_specs=P("shard", None),
                        out_specs=P("shard"), check=False))
    gs_f = jax.jit(sm(gsingle, m,
                      in_specs=(P("shard", None), P("shard", None)),
                      out_specs=P("shard"), check=False))
    cast_f = jax.jit(lambda x: x.astype(jnp.float32))
    return step_f, diag_f, gs_f, cast_f, row_sh


def _tiled_booleans(masks: np.ndarray, rw: np.ndarray, Np: int,
                    devs: List) -> np.ndarray:
    import jax

    from jepsen_tpu import parallel
    from jepsen_tpu.checkers import transfer

    # row blocks need Np % n_dev == 0 and Np is a power of two, so the
    # mesh uses the largest power-of-two PREFIX of the device order (3
    # chips -> 2) rather than refusing — or, worse, looping forever
    # growing Np against an odd divisor
    devs = parallel.device_order(devs)
    n_dev = 1 << (len(devs).bit_length() - 1)
    devs = devs[:n_dev]
    while Np % n_dev or Np < n_dev * 8:
        Np *= 2
    if masks.shape[1] != Np:                 # re-pad to the mesh grid
        grown = np.zeros((3, Np, Np), bool)
        grown[:, :masks.shape[1], :masks.shape[2]] = masks
        masks = grown
        grown_rw = np.zeros((Np, Np), bool)
        grown_rw[:rw.shape[0], :rw.shape[1]] = rw
        rw = grown_rw
    step_f, diag_f, gs_f, cast_f, row_sh = _tiled_calls(
        Np, n_dev, tuple(devs))
    # the tiled wire is uint8 (one byte per element — the row-sharded
    # put wants byte-addressable blocks; the sub-byte packing is the
    # single-chip path's), cast to f32 ON DEVICE; accounted as what
    # the link actually carries vs the blanket f32 reference
    transfer.count_put(int(masks.size + rw.size),
                       int((masks.size + rw.size) * 4))
    n_iter = max(1, math.ceil(math.log2(Np)))
    out = []
    C_wwwr = None
    for lane in range(3):
        M = cast_f(jax.device_put(masks[lane].astype(np.uint8),
                                  row_sh))
        for _ in range(n_iter):
            M = step_f(M)
        out.append(bool(np.asarray(diag_f(M)).any()))
        if lane == 1:
            C_wwwr = M
    Arw = cast_f(jax.device_put(rw.astype(np.uint8), row_sh))
    gs = bool(np.asarray(gs_f(Arw, C_wwwr)).any())
    obs.count("txn.closure.tiled")
    return np.asarray(out + [gs])
