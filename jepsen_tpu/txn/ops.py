"""Transactional operations — the Elle-style list-append / rw-register
workload shape (Kingsbury & Alvaro, *Elle*, VLDB 2020; upstream
``jepsen.tests.cycle.append``).

A transaction op is an :class:`~jepsen_tpu.op.Op` with ``f == "txn"``
whose value is a vector of micro-ops::

    [["append", k, v], ["r", k, [v1, v2, ...]]]

mirroring Elle's ``[[:append k v] [:r k vs]]``. On the invocation the
read micro-ops carry ``None`` (the observed version lives on the ``ok``
completion, exactly like register reads). The EDN round-trip rides
:mod:`jepsen_tpu.edn` unchanged — ``:append`` / ``:r`` parse to plain
strings and are written back as keywords.

This module provides the op constructors/validators, the
invoke/complete pairing (:func:`collect` — committed txns kept,
``fail`` txns set aside for G1a detection, ``info`` txns kept with
their reads untrusted), and :func:`pack_txns` — the dense int-tensor
encoding of a txn history (txn id / kind / key code / value code per
micro-op, flat read-version arrays) in the narrowest dtypes
:func:`jepsen_tpu.checkers.transfer.idx_dtype` admits, the same
narrow-wire discipline the dense-walk engines ship operands under.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu.models import Model, StepResult, inconsistent
from jepsen_tpu.op import Op
from jepsen_tpu.util import hashable

APPEND = "append"
READ = "r"

# read spellings accepted on the wire; canonicalized to READ
_READ_ALIASES = (READ, "read")


class MalformedTxn(ValueError):
    """A txn op whose value is not a vector of well-formed micro-ops."""


@dataclass(frozen=True, slots=True)
class ListAppend(Model):
    """Marker model routing a history to the TRANSACTIONAL checker
    (``facade.auto_check_txn``) instead of the linearizability engines.
    It carries no sequential step semantics — dependency-cycle search
    over the inferred wr/ww/rw graph replaces the state walk — so
    ``step`` refuses every op rather than pretend otherwise."""

    def step(self, op: Op) -> StepResult:
        return inconsistent(
            "ListAppend is a transactional model: route through "
            "facade.auto_check_txn, not the linearizable engines")


def list_append_model() -> ListAppend:
    return ListAppend()


def is_txn_op(op: Op) -> bool:
    return op.f == "txn"


def micro_ops(value: Any) -> List[Tuple[str, Any, Any]]:
    """Normalize a txn op value to ``[(kind, key, val), ...]`` with
    ``kind`` in {"append", "r"}; read vals are None (unobserved) or a
    list of observed values. Raises :class:`MalformedTxn` otherwise."""
    if not isinstance(value, (list, tuple)):
        raise MalformedTxn(f"txn value must be a vector, got {value!r}")
    out: List[Tuple[str, Any, Any]] = []
    for m in value:
        # tuple-unpack instead of isinstance+len: one bytecode op on
        # the well-formed path (this loop is ~15% of the 100k-txn
        # rung's host wall); a str of length 3 unpacks too, but its
        # chars then fail the kind dispatch below like any junk
        if type(m) is not list and type(m) is not tuple:
            raise MalformedTxn(f"micro-op must be [kind k v], got {m!r}")
        try:
            kind, k, v = m
        except ValueError:
            raise MalformedTxn(
                f"micro-op must be [kind k v], got {m!r}") from None
        if kind == APPEND:
            out.append((APPEND, k, v))
        elif kind in _READ_ALIASES:
            if v is None:
                out.append((READ, k, None))
            elif isinstance(v, (list, tuple)):
                out.append((READ, k, list(v)))
            else:
                raise MalformedTxn(f"read version must be a vector or "
                                   f"nil, got {v!r}")
        else:
            raise MalformedTxn(f"unknown micro-op kind {kind!r}")
    return out


def txn(process: Any, micros: Sequence[Sequence[Any]], type: str = "invoke",
        **kw: Any) -> Op:
    """Construct a txn op (type defaults to the invocation)."""
    return Op(process, type, "txn", [list(m) for m in micros], **kw)


@dataclass(frozen=True)
class Txn:
    """One logical transaction ready for dependency inference.

    ``tid`` is dense over the KEPT (ok + info) transactions; ``micros``
    come from the completion when the txn returned ``ok`` (reads
    carry their observed versions) and from the invocation otherwise
    (an ``info`` txn's reads stay ``None`` — a version observed by a
    crashed client never reached anyone and cannot order anything).

    ``end`` is the completion op's history index for ``ok`` txns and
    ``-1`` for crashed ones — the start/commit interval
    (``op.index``, ``end``) the snapshot-isolation lattice level turns
    into commit-order edges. A crashed txn has no commit point, so it
    emits no such edges.
    """
    tid: int
    op: Op
    micros: Tuple[Tuple[str, Any, Any], ...]
    crashed: bool
    end: int = -1

    @property
    def process(self) -> Any:
        return self.op.process

    @property
    def index(self) -> int:
        return self.op.index

    def describe(self) -> Dict[str, Any]:
        return {"txn": self.tid, "process": self.process,
                "index": self.index, "crashed": self.crashed,
                "value": [list(m) for m in self.micros]}


@dataclass(frozen=True)
class FailedTxn:
    """A ``fail`` txn — definitely took no effect, but its attempted
    appends matter: a read observing one is a G1a aborted read."""
    op: Op
    micros: Tuple[Tuple[str, Any, Any], ...]


def collect(history: Sequence[Op]
            ) -> Tuple[List[Txn], List[FailedTxn]]:
    """Pair txn invocations with completions: ``ok`` txns keep the
    completed micro-ops, ``info`` (crashed) txns keep the invoked ones
    with reads untrusted, ``fail`` txns go to the aborted-append side
    table. Non-txn ops (nemesis, mixed workloads) are skipped."""
    hist = list(history)
    if any(op.index < 0 for op in hist):
        hist = h.index(hist)
    txns: List[Txn] = []
    fails: List[FailedTxn] = []
    for p in h.pair(hist):
        inv = p.invoke
        if not is_txn_op(inv):
            continue
        if p.failed:
            fails.append(FailedTxn(op=inv, micros=tuple(
                micro_ops(inv.value))))
            continue
        comp = p.complete
        value = inv.value
        if comp is not None and comp.type == "ok" \
                and comp.value is not None:
            value = comp.value
        micros = tuple(micro_ops(value))
        if p.crashed:
            # reads of a crashed txn never returned: blank them so the
            # inference cannot trust a version nobody observed
            micros = tuple((k, key, None) if k == READ else (k, key, v)
                           for k, key, v in micros)
        # the invocation op identifies the txn (process/index); the
        # completed micro-ops live in ``micros`` — grafting the
        # completed value back onto the op (a dataclasses.replace per
        # txn) was ~25% of collect at the 100k rung, for a field no
        # consumer reads
        end = -1
        if not p.crashed and comp is not None and comp.index >= 0:
            end = comp.index
        txns.append(Txn(tid=len(txns), op=inv,
                        micros=micros, crashed=p.crashed, end=end))
    return txns, fails


@dataclass(frozen=True)
class PackedTxns:
    """Dense int encoding of a txn history (structure-of-arrays, like
    :class:`~jepsen_tpu.history.PackedHistory` for the linear engines):
    one row per micro-op, keys and per-key append values int-coded,
    read versions flattened into one code array with offset/length
    indexing. Every array ships in the narrowest signed dtype
    ``transfer.idx_dtype`` admits for its code space, so a txn history
    crosses the wire on the same diet as the dense-walk operands."""
    n_txns: int
    n_micros: int
    txn_id: np.ndarray       # idx[n_micros]
    kind: np.ndarray         # i8[n_micros]; 0 = append, 1 = read
    key_id: np.ndarray       # idx[n_micros]
    val_code: np.ndarray     # idx[n_micros]; appends only, reads -1
    read_off: np.ndarray     # i32[n_micros]; reads only, else -1
    read_len: np.ndarray     # idx[n_micros]; -1 = unknown read
    read_vals: np.ndarray    # idx[sum read lens]
    keys: Tuple[Any, ...]            # key_id -> key
    key_vals: Tuple[Tuple[Any, ...], ...]  # key_id -> (code -> value)

    @property
    def wire_bytes(self) -> int:
        return sum(int(a.nbytes) for a in
                   (self.txn_id, self.kind, self.key_id, self.val_code,
                    self.read_off, self.read_len, self.read_vals))


KIND_APPEND = 0
KIND_READ = 1


def pack_txns(txns: Sequence[Txn]) -> PackedTxns:
    """Int-code a collected txn history into dense tensors."""
    from jepsen_tpu.checkers import transfer

    keys: Dict[Any, int] = {}
    vals: List[Dict[Any, int]] = []          # per key: value -> code

    def key_code(k: Any) -> int:
        hk = hashable(k)
        if hk not in keys:
            keys[hk] = len(keys)
            vals.append({})
        return keys[hk]

    def val_code_of(kid: int, v: Any) -> int:
        hv = hashable(v)
        m = vals[kid]
        if hv not in m:
            m[hv] = len(m)
        return m[hv]

    rows: List[Tuple[int, int, int, int, int, int]] = []
    read_flat: List[int] = []
    for t in txns:
        for kind, k, v in t.micros:
            kid = key_code(k)
            if kind == APPEND:
                rows.append((t.tid, KIND_APPEND, kid,
                             val_code_of(kid, v), -1, -1))
            else:
                if v is None:
                    rows.append((t.tid, KIND_READ, kid, -1, -1, -1))
                else:
                    off = len(read_flat)
                    read_flat.extend(val_code_of(kid, x) for x in v)
                    rows.append((t.tid, KIND_READ, kid, -1, off, len(v)))
    n_micros = len(rows)
    arr = np.asarray(rows, np.int64).reshape(n_micros, 6)
    max_val = max([1] + [len(m) for m in vals])
    # narrowest signed dtypes for each code space (accounting-only
    # probes pass count=False elsewhere; THIS is the production wire)
    dt_tid = transfer.idx_dtype(max(len(txns), 1))
    dt_key = transfer.idx_dtype(max(len(keys), 1))
    dt_val = transfer.idx_dtype(max_val)
    dt_len = transfer.idx_dtype(max([1] + [r[5] for r in rows]))
    return PackedTxns(
        n_txns=len(txns), n_micros=n_micros,
        txn_id=arr[:, 0].astype(dt_tid),
        kind=arr[:, 1].astype(np.int8),
        key_id=arr[:, 2].astype(dt_key),
        val_code=arr[:, 3].astype(dt_val),
        read_off=arr[:, 4].astype(np.int32),
        read_len=arr[:, 5].astype(dt_len),
        read_vals=np.asarray(read_flat, np.int64).astype(dt_val),
        keys=tuple(sorted(keys, key=lambda k: keys[k])),
        key_vals=tuple(tuple(sorted(m, key=lambda v: m[v]))
                       for m in vals))
