"""The consistency-model lattice (ISSUE 17): one parameterized word
closure answers "WHICH guarantee broke", not just "serializable or
not".

Levels, weakest first::

    read-committed ⊏ causal ⊏ pl-2 ⊏ si ⊏ serializable

Each level maps to the edge-class masks allowed to close a cycle plus
host-side scans, evaluated CUMULATIVELY: a level proscribes its own
anomaly classes and everything below it, so ``holds`` is monotone by
construction (``holds[stronger] ⇒ holds[weaker]``). That resolves the
classical incomparability of snapshot isolation and serializability —
the top of this lattice is the strong-session reading of each level
(the one a safety-testing service actually wants: real systems that
claim a level also respect commit order and per-session monotonicity).

Newly proscribed per level:

- ``read-committed`` — the direct anomalies (G1a aborted read,
  duplicate appends, non-prefix reads — these fail EVERY level) and
  G0 (``ww`` cycles);
- ``causal``         — G1c (``ww ∪ wr`` cycles);
- ``pl-2``           — the four session guarantees, checked as cheap
  host prefix scans over the recovered orders: monotonic reads,
  monotonic writes, read-your-writes, writes-follow-reads;
- ``si``             — the G-SI write-skew taxonomy on the
  ``ww ∪ wr ∪ cm`` lane (``cm`` = commit-order edges from
  :func:`jepsen_tpu.txn.infer.commit_mask`): G-SIa (a dependency edge
  contradicting commit order), G-SIb (one rw edge closing a
  commit-order cycle — write skew between non-overlapping txns),
  G-SI (any other cycle in the lane);
- ``serializable``   — G-single and G2 (any dependency cycle).

All six device booleans come from ONE ``[K, Np, NW]`` squaring ladder
(:func:`jepsen_tpu.txn.cycles.lattice_booleans` — checking five
levels costs one closure, not five), with the f32 einsum body as the
recorded fallback and :func:`jepsen_tpu.txn.host_ref.
lattice_classify_booleans` as the host reference, bit-identical and
differentially tested. Witness walks are host-side and shared by
every engine path.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.txn import cycles, host_ref
from jepsen_tpu.txn.infer import DepGraph
from jepsen_tpu.txn.ops import APPEND, READ
from jepsen_tpu.util import hashable, hashable_seq

LEVELS = ("read-committed", "causal", "pl-2", "si", "serializable")

# accepted spellings -> canonical level key
_ALIASES = {
    "read-committed": "read-committed", "rc": "read-committed",
    "pl-2": "pl-2", "pl2": "pl-2",
    "causal": "causal",
    "si": "si", "snapshot-isolation": "si",
    "serializable": "serializable", "serializability": "serializable",
    "all": "all",
}

# session-guarantee violation types (the pl-2 scans)
SESSION_CLASSES = ("monotonic-reads", "monotonic-writes",
                   "read-your-writes", "writes-follow-reads")

# level -> anomaly classes it NEWLY proscribes (cumulative semantics:
# a level also proscribes everything weaker levels do)
LEVEL_ANOMALIES: Dict[str, Tuple[str, ...]] = {
    "read-committed": ("G0",),
    "causal": ("G1c",),
    "pl-2": SESSION_CLASSES,
    "si": ("G-SIa", "G-SIb", "G-SI"),
    "serializable": ("G-single", "G2"),
}


def canon_level(level: Any) -> str:
    """Canonicalize a requested consistency level (str, or a sequence
    of strs meaning "check these" — canonicalized elementwise by the
    caller). Raises ValueError on junk so serve/facade reject early."""
    if not isinstance(level, str) or level.lower() not in _ALIASES:
        raise ValueError(
            f"unknown consistency level {level!r}; expected one of "
            f"{sorted(set(_ALIASES))}")
    return _ALIASES[level.lower()]


def canon_levels(consistency: Any) -> Tuple[str, ...]:
    """A requested level, list of levels, or ``"all"`` -> the
    canonical tuple of levels the verdict gates on."""
    if isinstance(consistency, (list, tuple, set)):
        out = tuple(sorted({canon_level(x) for x in consistency},
                           key=LEVELS.index))
        if not out:
            raise ValueError("empty consistency level set")
        return out
    c = canon_level(consistency)
    return LEVELS if c == "all" else (c,)


def holds_from(booleans: Dict[str, bool], *, direct: bool = False,
               session_violated: bool = False) -> Dict[str, bool]:
    """Cumulative per-level verdicts from the six lattice booleans
    plus the host-scan facts. Monotone by construction. (G-SIa needs
    no separate input: its witness pattern is a 2-cycle in the
    ``ww ∪ wr ∪ cm`` lane, so ``cyc_si`` already covers it.)"""
    fail_rc = direct or booleans["cyc_ww"]
    fail_causal = fail_rc or booleans["cyc_wwwr"]
    fail_pl2 = fail_causal or session_violated
    fail_si = fail_pl2 or booleans.get("cyc_si", False) \
        or booleans.get("gsib", False)
    fail_ser = fail_si or booleans["cyc_full"] or booleans["gsingle"]
    return {"read-committed": not fail_rc, "causal": not fail_causal,
            "pl-2": not fail_pl2, "si": not fail_si,
            "serializable": not fail_ser}


def weakest_violated(holds: Dict[str, bool]) -> Optional[str]:
    for lvl in LEVELS:
        if not holds.get(lvl, True):
            return lvl
    return None


def all_false_holds() -> Dict[str, bool]:
    """Every level fails — the direct-anomaly short-circuit (aborted
    reads / duplicate appends / non-prefix reads poison all levels)."""
    return {lvl: False for lvl in LEVELS}


# -- session-guarantee scans (the pl-2 level) ----------------------------

def session_scans(txns: Sequence[Any]) -> List[Dict[str, Any]]:
    """Per-process session-guarantee violations as host prefix scans
    over the recovered orders — O(history), no device work.

    Soundness: only committed (non-crashed) txns participate; reads
    are compared by observed CONTENT (a later read must contain the
    process's own earlier appends and never shrink), and positional
    checks (monotonic writes, writes-follow-reads) only fire for
    appends some read actually recovered. Violations are monotone
    under history extension, so the streaming session can re-run the
    scan per block and never retract a verdict."""
    # recovered order per key: the longest observed read
    orders: Dict[Any, Tuple[Any, ...]] = {}
    for t in txns:
        for kind, k, v in t.micros:
            if kind == READ and v is not None:
                hk = hashable(k)
                hv = hashable_seq(v)
                if len(hv) > len(orders.get(hk, ())):
                    orders[hk] = hv
    pos: Dict[Any, Dict[Any, int]] = {
        hk: {v: i for i, v in enumerate(vs)}
        for hk, vs in orders.items()}

    by_proc: Dict[Any, List[Any]] = {}
    for t in txns:
        if not t.crashed:
            by_proc.setdefault(hashable(t.process), []).append(t)

    out: List[Dict[str, Any]] = []
    for proc in sorted(by_proc, key=lambda p: (str(type(p)), str(p))):
        max_read: Dict[Any, Tuple[int, int]] = {}   # key -> (len, tid)
        own: Dict[Any, List[Tuple[Any, int]]] = {}  # key -> [(val, tid)]
        last_pos: Dict[Any, Tuple[int, int]] = {}   # key -> (pos, tid)
        for t in by_proc[proc]:                     # tid order = program order
            appends_now: List[Tuple[Any, Any]] = []
            for kind, k, v in t.micros:
                hk = hashable(k)
                if kind == READ and v is not None:
                    vs = hashable_seq(v)
                    L = len(vs)
                    prev = max_read.get(hk)
                    if prev is not None and L < prev[0]:
                        out.append({
                            "type": "monotonic-reads", "process": proc,
                            "key": k, "txns": [prev[1], t.tid],
                            "lens": [prev[0], L]})
                    if prev is None or L > prev[0]:
                        max_read[hk] = (L, t.tid)
                    seen = set(vs)
                    for av, atid in own.get(hk, ()):
                        if av not in seen:
                            out.append({
                                "type": "read-your-writes",
                                "process": proc, "key": k, "value": av,
                                "txns": [atid, t.tid]})
                elif kind == APPEND:
                    hv = hashable(v)
                    p = pos.get(hk, {}).get(hv)
                    if p is not None:
                        lp = last_pos.get(hk)
                        if lp is not None and p < lp[0]:
                            out.append({
                                "type": "monotonic-writes",
                                "process": proc, "key": k, "value": v,
                                "txns": [lp[1], t.tid],
                                "positions": [lp[0], p]})
                        last_pos[hk] = (p, t.tid)
                        mr = max_read.get(hk)
                        if mr is not None and p < mr[0]:
                            out.append({
                                "type": "writes-follow-reads",
                                "process": proc, "key": k, "value": v,
                                "txns": [mr[1], t.tid],
                                "position": p, "read-len": mr[0]})
                    appends_now.append((hk, hv))
            # own appends join AFTER the txn: read-your-writes is an
            # ACROSS-txn guarantee (intra-txn read-after-append is the
            # direct prefix machinery's business)
            for hk, hv in appends_now:
                own.setdefault(hk, []).append((hv, t.tid))
    if out:
        obs.count("txn.lattice.scan_violations", len(out))
    return out


# -- per-level classification --------------------------------------------

def _class_presence(booleans: Dict[str, bool],
                    scans: List[Dict[str, Any]],
                    gsia: bool) -> Dict[str, bool]:
    """Anomaly class -> present, with the same implied-by-stronger
    suppression discipline as :func:`host_ref.derive_anomalies`."""
    scan_types = {s["type"] for s in scans}
    p = {
        "G0": booleans["cyc_ww"],
        "G1c": booleans["cyc_wwwr"] and not booleans["cyc_ww"],
        "G-single": booleans["gsingle"] and not booleans["cyc_wwwr"],
        "G2": booleans["cyc_full"] and not (booleans["cyc_wwwr"]
                                            or booleans["gsingle"]),
        "G-SIa": gsia,
        "G-SIb": booleans.get("gsib", False),
        "G-SI": booleans.get("cyc_si", False)
                and not gsia and not booleans["cyc_wwwr"],
    }
    for c in SESSION_CLASSES:
        p[c] = c in scan_types
    return p


def check_levels(graph: DepGraph, *,
                 devices: Optional[Sequence] = None,
                 max_dense_txns: Optional[int] = None,
                 force_host: bool = False,
                 starts: Optional[np.ndarray] = None,
                 ends: Optional[np.ndarray] = None) -> Dict[str, Any]:
    """Evaluate every lattice level over one inferred graph: ONE
    device closure (six booleans), the host session scans, the G-SIa
    edge scan, per-level holds/anomalies/witnesses. ``starts``/
    ``ends`` override the txn intervals (the streaming session passes
    its own stream positions); post-hoc they come off the ``Txn``
    records. Graphs past the dense envelope go straight to the host
    lattice reference (the commit-order lane cannot ride the
    cycle-preserving Kahn trim: cm edges through trimmed nodes would
    vanish) — a recorded route, not a fallback."""
    import logging
    log = logging.getLogger("jepsen.txn")

    if starts is None:
        starts = np.asarray([t.index for t in graph.txns], np.int64)
    if ends is None:
        ends = np.asarray([t.end for t in graph.txns], np.int64)
    obs.count("txn.lattice.check")

    booleans: Optional[Dict[str, bool]] = None
    engine = "txn-lattice-host"
    if graph.e == 0:
        # no dependency edges: nothing can cycle (cm alone is an
        # interval order — acyclic), but the session scans still run
        booleans = {k: False for k in cycles.LATTICE_KEYS}
        engine = "txn-lattice-noedges"
    elif force_host or not cycles.device_enabled():
        obs.decision("txn-lattice", "route", cause="host-forced",
                     txns=graph.n, edges=graph.e)
    else:
        cap = max_dense_txns if max_dense_txns is not None \
            else cycles.max_dense()
        if not cycles.admits(graph.n, cap):
            obs.decision("txn-lattice", "route", cause="past-envelope",
                         txns=graph.n, edges=graph.e)
        else:
            cm = _cm_from(starts, ends)
            try:
                booleans = cycles.lattice_booleans(graph, cm,
                                                   devices=devices)
                engine = "txn-lattice-mxu"
            except Exception as e:                      # noqa: BLE001
                log.warning("txn lattice closure failed (%r); host "
                            "lattice fallback", e, exc_info=e)
                obs.engine_fallback("txn-lattice", type(e).__name__,
                                    txns=graph.n, edges=graph.e)
                booleans = None
    if booleans is None:
        booleans = dict(host_ref.classify_booleans(graph))
        booleans.update(host_ref.lattice_classify_booleans(
            graph, starts, ends))
        engine = "txn-lattice-host"
        obs.count("txn.lattice.host")

    scans = session_scans(graph.txns)
    gsia_w = host_ref.gsia_scan(graph, starts, ends)
    holds = holds_from(booleans,
                       session_violated=bool(scans))
    presence = _class_presence(booleans, scans, gsia_w is not None)

    levels: Dict[str, Any] = {}
    for lvl in LEVELS:
        found = [c for c in LEVEL_ANOMALIES[lvl] if presence.get(c)]
        d: Dict[str, Any] = {"holds": holds[lvl], "anomalies": found}
        if found:
            d["witness"] = _witness(graph, found[0], scans,
                                    starts, ends, gsia_w)
        levels[lvl] = d
    wv = weakest_violated(holds)
    if wv is not None:
        obs.count("txn.lattice.violations")
    return {"booleans": booleans, "holds": holds, "levels": levels,
            "weakest-violated": wv, "engine": engine,
            "session-violations": [dict(s) for s in scans[:32]]}


def _cm_from(starts: np.ndarray, ends: np.ndarray) -> np.ndarray:
    cm = (ends >= 0)[:, None] & (ends[:, None] < starts[None, :])
    np.fill_diagonal(cm, False)
    return cm


def _witness(graph: DepGraph, cls: str, scans: List[Dict[str, Any]],
             starts: np.ndarray, ends: np.ndarray,
             gsia_w: Optional[Dict[str, Any]]
             ) -> Optional[Dict[str, Any]]:
    """The shared host-side witness walk for every anomaly class the
    lattice reports (identical across device/f32/host verdict paths —
    witnesses never depend on which body computed the booleans)."""
    if cls in SESSION_CLASSES:
        for s in scans:
            if s["type"] == cls:
                return dict(s)
        return None
    if cls == "G-SIa":
        return gsia_w
    if cls in ("G-SIb", "G-SI"):
        return host_ref.find_lattice_witness(graph, cls, starts, ends)
    return host_ref.find_witness(graph, cls)
