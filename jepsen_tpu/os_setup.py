"""OS automation — upstream ``jepsen/src/jepsen/os.clj`` + ``os/debian.clj``
``os/centos.clj`` ``os/ubuntu.clj`` (SURVEY.md §2.1, L1): prepare each node's
operating system before the DB is installed.
"""
from __future__ import annotations

from typing import Mapping, Sequence

from jepsen_tpu import control


class OS:
    """Base OS (upstream ``jepsen.os/OS`` protocol); default no-op
    (upstream ``jepsen.os/noop``)."""

    def setup(self, test: Mapping, node: str) -> None:
        pass

    def teardown(self, test: Mapping, node: str) -> None:
        pass


class NoopOS(OS):
    pass


def noop() -> NoopOS:
    return NoopOS()


class DebianOS(OS):
    """Debian/Ubuntu prep (upstream ``jepsen.os.debian``): hostname, apt
    update (cached), base packages."""

    def __init__(self, packages: Sequence[str] = ("wget", "curl", "unzip",
                                                  "iptables", "psmisc",
                                                  "tar", "bzip2",
                                                  "ntpdate", "faketime")):
        self.packages = list(packages)

    def setup(self, test, node):
        s = control.session(test, node).su()
        s.exec_raw(f"hostname {control.escape(node)}")
        missing = [p for p in self.packages if s.exec_raw(
            f"dpkg -s {p} >/dev/null 2>&1").exit_code != 0]
        if missing:
            s.exec_raw("apt-get -qy update")
            s.exec("env", "DEBIAN_FRONTEND=noninteractive", "apt-get",
                   "-qy", "install", *missing)


class CentosOS(OS):
    """RHEL-family prep (upstream ``jepsen.os.centos``)."""

    def __init__(self, packages: Sequence[str] = ("wget", "curl", "unzip",
                                                  "iptables", "psmisc",
                                                  "tar", "bzip2")):
        self.packages = list(packages)

    def setup(self, test, node):
        s = control.session(test, node).su()
        s.exec_raw(f"hostname {control.escape(node)}")
        s.exec_raw("yum -y -q install " + " ".join(self.packages))


def debian() -> DebianOS:
    return DebianOS()


def ubuntu() -> DebianOS:
    """Ubuntu uses the Debian toolchain (upstream ``jepsen.os.ubuntu`` is
    a thin wrapper over the debian ns)."""
    return DebianOS()


def centos() -> CentosOS:
    return CentosOS()


def setup_all(test: Mapping) -> None:
    os_ = test.get("os")
    if os_ is None:
        return
    control.on_nodes(test, lambda s, node: os_.setup(test, node))


def teardown_all(test: Mapping) -> None:
    os_ = test.get("os")
    if os_ is None:
        return
    control.on_nodes(test, lambda s, node: os_.teardown(test, node))
