"""Base test map — upstream ``jepsen/src/jepsen/tests.clj``
(SURVEY.md §2.1): ``noop_test`` is the canonical minimal test every suite
merges over.
"""
from __future__ import annotations

from typing import Any, Dict

from jepsen_tpu.checkers.facade import unbridled_optimism
from jepsen_tpu.client import noop_client


def noop_test() -> Dict[str, Any]:
    """A test that does nothing, successfully (upstream
    ``jepsen.tests/noop-test``)."""
    return {
        "name": "noop",
        "nodes": [],
        "concurrency": 1,
        "os": None,
        "db": None,
        "client": noop_client(),
        "nemesis": None,
        "generator": None,
        "checker": unbridled_optimism(),
        "model": None,
        "ssh": {},
        "store": True,
        "store-root": "store",
    }
