"""Device-mesh parallelism for the checker searches.

No upstream analogue: the reference's analysis is single-JVM
(``knossos.competition`` merely races two algorithms on two threads —
SURVEY.md §2.4). Here the scaling axes are native to the hardware:

- **key axis** — per-key sub-histories (``jepsen.independent`` semantics)
  are independent searches: shard the batch over the mesh, one vmapped walk
  per device, no communication until the final validity reduction.
- **chunk axis** — a single long history splits into event chunks whose
  boolean transfer matrices are computed in parallel (basis-batched walks)
  and composed; the composition is associative, so chunks shard cleanly
  and combine with an all-gather of small D×D matrices over ICI.

Collectives ride XLA (``psum`` for validity reductions, ``all_gather`` for
matrix combination); there is no NCCL/MPI-style backend to port — the mesh
IS the communication layer.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def devices(platform: Optional[str] = None) -> list:
    import jax
    return jax.devices(platform)


def mesh(axis: str = "shard", devs: Optional[Sequence] = None):
    """A 1-D mesh over ``devs`` (default: all devices)."""
    import jax
    from jax.sharding import Mesh
    devs = list(devs) if devs is not None else jax.devices()
    return Mesh(np.array(devs), (axis,))


def shard_map(fn, mesh, in_specs, out_specs,
              check: Optional[bool] = None):
    """``jax.shard_map`` across jax versions: the top-level API with
    ``check_vma`` (jax >= 0.6) or the 0.4 experimental module with its
    ``check_rep`` spelling — one call site for every sharded engine so
    a jax upgrade touches only this shim. ``check=None`` keeps the
    library default; False skips the replication/varying-axes check."""
    import jax
    kw = {} if check is None else (
        {"check_vma": check} if hasattr(jax, "shard_map")
        else {"check_rep": check})
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def device_order(devs: Optional[Sequence] = None,
                 axis: str = "shard") -> list:
    """Canonical device placement order for block-sharded lanes: the
    ravel order of the 1-D :func:`mesh` over ``devs`` — the same order
    a ``NamedSharding(mesh, P(axis))`` assigns leading-axis blocks, so
    per-device dispatches (the mesh lockstep lane's lane blocks) and
    NamedSharding placements (the keyed mesh lanes) put block k on the
    same device."""
    return list(mesh(axis, devs).devices.ravel())


def shard_leading_axis(arrays, devs: Optional[Sequence] = None):
    """Place each array with its leading axis sharded across ``devs``
    (padding to a multiple of the device count is the caller's job)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    m = mesh("shard", devs)
    s = NamedSharding(m, P("shard"))
    return [jax.device_put(a, s) for a in arrays]


def chunked_transfer(args, devs: Sequence):
    """Compute per-chunk transfer matrices with the chunk axis sharded over
    ``devs`` via ``shard_map``. ``args`` = (P_mats, xor_cols, bitmask,
    ret_slot_c, slot_ops_c, basis_c) as built by
    :func:`jepsen_tpu.checkers.reach.check_chunked`; the transition
    matrices and static index maps are replicated, the chunked return
    streams and basis blocks are chunk-sharded. Returns a host ndarray
    [n_chunks, D, D]."""
    import jax
    from jax.sharding import PartitionSpec as P

    from jepsen_tpu.checkers import reach

    P_mats, xor_cols, bitmask, ret_slot_c, slot_ops_c, basis_c = args
    n_chunks = ret_slot_c.shape[0]
    n_dev = len(devs)
    if n_chunks % n_dev:
        raise ValueError(f"n_chunks {n_chunks} not divisible by "
                         f"{n_dev} devices")
    m = mesh("chunks", devs)

    def local(P_mats, xor_cols, bitmask, ret_slot_c, slot_ops_c, basis_c):
        inner = jax.vmap(reach._walk_returns_scan,
                         in_axes=(None, None, None, None, None, 0))
        outer = jax.vmap(inner, in_axes=(None, None, None, 0, 0, 0))
        return outer(P_mats, xor_cols, bitmask, ret_slot_c, slot_ops_c,
                     basis_c)

    # replicated operands mix invariant/variant axes inside control
    # flow; skip the varying-axes check
    sm = shard_map(
        local, m,
        in_specs=(P(), P(), P(), P("chunks"), P("chunks"), P("chunks")),
        out_specs=P("chunks"), check=False)
    R = jax.jit(sm)(P_mats, xor_cols, bitmask, ret_slot_c, slot_ops_c,
                    basis_c)
    # [n_chunks, B, S, M] -> [n_chunks, B, D]; B is the (possibly
    # reachability-restricted) basis row count, D = S·M. The fetch
    # goes through reach._fetch: in a multi-process run the sharded
    # result spans non-addressable devices and needs process_allgather
    return reach._fetch(R).reshape(R.shape[0], R.shape[1], -1)
