"""Multi-host distribution — the scale-out story (SURVEY.md §2.4, §5).

The reference has no data-plane communication backend at all: its
analysis is single-JVM, and its only cross-machine traffic is the SSH
control plane (``jepsen.control``). The TPU-native equivalents:

- **control plane** — unchanged in spirit: :mod:`jepsen_tpu.control`
  drives DB nodes over SSH.
- **data plane** — single-controller JAX inside one host;
  ``jax.distributed`` + a hybrid ICI×DCN mesh across hosts. Collectives
  are XLA's (``psum`` liveness reductions, ``all_gather`` of transfer
  matrices); shardings are laid out so the hot axes (keys, chunks) ride
  ICI within a slice and only the final scalar reductions cross DCN.

Usage on each host of a multi-host TPU slice::

    from jepsen_tpu.parallel import distributed
    distributed.initialize()            # env-driven on TPU pods
    mesh = distributed.hybrid_mesh(("dcn", "keys"))
    results = reach.check_many(model, packs, devices=mesh.devices.ravel())

Everything here degrades gracefully to single-process: ``initialize``
is a no-op when no coordinator is configured, and ``hybrid_mesh`` of a
single host is an ordinary 1-slice mesh.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Bring up ``jax.distributed`` for multi-host runs.

    On TPU pods all three arguments are discovered from the environment
    (the standard JAX bootstrap); pass them explicitly for CPU/GPU
    fleets. Returns True if a distributed runtime is (now) active,
    False when running single-process (no coordinator configured) —
    callers need no branching, every mesh helper below works either
    way."""
    global _initialized
    if _initialized:
        return True
    workers = [w for w in
               os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if w]
    if (coordinator_address is None and num_processes is None
            and "JAX_COORDINATOR_ADDRESS" not in os.environ
            and "MEGASCALE_COORDINATOR_ADDRESS" not in os.environ
            and len(workers) < 2):      # one hostname = single host
        return False                    # single-process: nothing to do
    import jax
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except (ValueError, RuntimeError):
        # auto-detection came up empty (or already initialized by the
        # launcher) — stay single-process rather than crash the check
        return False
    _initialized = True
    return True


def process_info() -> Tuple[int, int]:
    """(process_index, process_count) — (0, 1) when single-process."""
    import jax
    return jax.process_index(), jax.process_count()


def hybrid_mesh(axis_names: Tuple[str, str] = ("dcn", "ici"),
                devices: Optional[Sequence] = None):
    """A 2-D mesh [hosts(DCN) × per-host devices(ICI)].

    The outer axis crosses host boundaries (DCN-speed collectives —
    keep it for scalar reductions and rare rebalances); the inner axis
    stays within a slice (ICI-speed — shard the hot batch axes here).
    Falls back to a 1×N mesh in single-host runs, so shardings written
    against these axis names work unchanged everywhere."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    n_proc = max(jax.process_count(), 1)
    per_host = len(devs) // n_proc
    if n_proc > 1 and per_host * n_proc == len(devs):
        try:
            from jax.experimental import mesh_utils
            arr = mesh_utils.create_hybrid_device_mesh(
                (per_host,), (n_proc,), devices=devs)
            return Mesh(arr.reshape(n_proc, per_host), axis_names)
        except Exception:                               # noqa: BLE001
            pass                        # topology discovery unavailable
        # no physical topology (e.g. the CPU backend in multi-process
        # tests): group rows by owning process — that IS the host
        # boundary the outer axis models, so collectives along the
        # inner axis stay process-local wherever the runtime allows
        by_proc = sorted(devs, key=lambda d: (d.process_index, d.id))
        if (len({d.process_index for d in devs}) == n_proc
                and all(d.process_index
                        == by_proc[(i // per_host) * per_host]
                        .process_index
                        for i, d in enumerate(by_proc))):
            return Mesh(np.array(by_proc).reshape(n_proc, per_host),
                        axis_names)
    return Mesh(np.array(devs).reshape(1, len(devs)), axis_names)


def keys_sharding(mesh, batch_axis: str = "ici"):
    """NamedSharding placing a leading key/chunk axis on the ICI axis of
    a :func:`hybrid_mesh` (replicated across DCN)."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(batch_axis))
