"""Multi-host distribution — the scale-out story (SURVEY.md §2.4, §5).

The reference has no data-plane communication backend at all: its
analysis is single-JVM, and its only cross-machine traffic is the SSH
control plane (``jepsen.control``). The TPU-native equivalents:

- **control plane** — unchanged in spirit: :mod:`jepsen_tpu.control`
  drives DB nodes over SSH.
- **data plane** — single-controller JAX inside one host;
  ``jax.distributed`` + a hybrid ICI×DCN mesh across hosts. Collectives
  are XLA's (``psum`` liveness reductions, ``all_gather`` of transfer
  matrices); shardings are laid out so the hot axes (keys, chunks) ride
  ICI within a slice and only the final scalar reductions cross DCN.

Usage on each host of a multi-host TPU slice::

    from jepsen_tpu.parallel import distributed
    distributed.initialize()            # env-driven on TPU pods
    mesh = distributed.hybrid_mesh(("dcn", "keys"))
    results = reach.check_many(model, packs, devices=mesh.devices.ravel())

Everything here degrades gracefully to single-process: ``initialize``
is a no-op when no coordinator is configured, and ``hybrid_mesh`` of a
single host is an ordinary 1-slice mesh.
"""
from __future__ import annotations

import os
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

_initialized = False

# wall-clock deadline on cross-host collectives (the gather is the
# ONLY blocking dependency one process has on its peers; past this it
# is treated as a dead peer and the caller's exact-rescue engages)
_TIMEOUT_ENV = "JEPSEN_TPU_DIST_TIMEOUT_S"


def gather_timeout_s() -> float:
    try:
        return float(os.environ.get(_TIMEOUT_ENV, "") or 120.0)
    except ValueError:
        return 120.0


class DistGatherError(RuntimeError):
    """A cross-host gather failed or timed out (dead peer, torn
    coordinator) — callers fall back to local re-derivation."""


# pod driver mode: the multi-controller runtime wants every process to
# run the same program, but a pod DAEMON is single-controller — only
# rank 0 holds the HTTP socket and the work. Driver mode bridges the
# two: rank 0 ships each multi-host walk's operands to the compute
# peers over the work channel below, so every rank enters the same
# walk and the gather collective rendezvouses. Off (the default) for
# SPMD callers — tests and dryruns where every rank already runs the
# same code.
_DRIVER = False
_DRIVER_LOCK = threading.RLock()


def set_driver(on: bool) -> None:
    global _DRIVER
    _DRIVER = bool(on)


def driver_mode() -> bool:
    return _DRIVER


def driver_lock() -> threading.RLock:
    """Held by rank 0 across ship-operands + gather of one walk:
    collectives are matched by issue order, so two concurrent checks
    interleaving theirs would cross-wire every rank."""
    return _DRIVER_LOCK


def _bcast(arr: np.ndarray, timeout_s: Optional[float] = None
           ) -> np.ndarray:
    """``broadcast_one_to_all`` with an optional wall-clock deadline
    (same abandon-the-stuck-thread pattern as :meth:`ChunkShard.gather`
    — a dead peer must cost bounded wall clock, never a hang)."""
    box: dict = {}

    def run() -> None:
        try:
            from jax.experimental import multihost_utils
            box["out"] = np.asarray(
                multihost_utils.broadcast_one_to_all(arr))
        except BaseException as e:                  # noqa: BLE001
            box["err"] = e

    if timeout_s is None:
        run()
    else:
        t = threading.Thread(target=run, daemon=True,
                             name="jepsen-dist-bcast")
        t.start()
        t.join(timeout_s)
    if "out" in box:
        return box["out"]
    if "err" in box:
        raise DistGatherError(
            f"broadcast failed: {box['err']!r}") from box["err"]
    raise DistGatherError(f"broadcast timed out after {timeout_s}s")


def send_work(item: dict, timeout_s: Optional[float] = None) -> None:
    """Rank 0: ship one work item (a dict of numpy arrays / scalars /
    short strings) to every compute peer blocked in :func:`recv_work`.
    Two broadcasts — payload length, then the npz bytes — because
    every rank must present same-shaped operands to a collective.
    Raises :class:`DistGatherError` on a torn pod."""
    import io

    buf = io.BytesIO()
    np.savez(buf, **item)
    data = np.frombuffer(buf.getvalue(), np.uint8)
    _bcast(np.array([data.size], np.int64), timeout_s)
    _bcast(data, timeout_s)


def recv_work() -> dict:
    """Ranks > 0: block until rank 0 ships the next work item (the
    compute-peer loop's sole wait state)."""
    import io

    n = int(_bcast(np.zeros(1, np.int64))[0])
    # the broadcast may hand the bytes back in a widened compute dtype
    # (its reduction path upcasts on some backends) — values are exact,
    # so coerce back to the uint8 wire before reparsing the npz
    data = _bcast(np.zeros(n, np.uint8)).astype(np.uint8)
    with np.load(io.BytesIO(data.tobytes()),
                 allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Bring up ``jax.distributed`` for multi-host runs.

    On TPU pods all three arguments are discovered from the environment
    (the standard JAX bootstrap); pass them explicitly for CPU/GPU
    fleets. Returns True if a distributed runtime is (now) active,
    False when running single-process (no coordinator configured) —
    callers need no branching, every mesh helper below works either
    way."""
    global _initialized
    if _initialized:
        return True
    workers = [w for w in
               os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if w]
    if (coordinator_address is None and num_processes is None
            and "JAX_COORDINATOR_ADDRESS" not in os.environ
            and "MEGASCALE_COORDINATOR_ADDRESS" not in os.environ
            and len(workers) < 2):      # one hostname = single host
        return False                    # single-process: nothing to do
    import jax
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        # CPU fleets (tests, the dist-smoke CI job) need an explicit
        # collectives backend; gloo ships with jaxlib. Must be set
        # before the first backend spins up.
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
        except Exception:                           # noqa: BLE001
            pass                    # older jaxlib: single-process only
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except (ValueError, RuntimeError):
        # auto-detection came up empty (or already initialized by the
        # launcher) — stay single-process rather than crash the check
        return False
    _initialized = True
    return True


def process_info() -> Tuple[int, int]:
    """(process_index, process_count) — (0, 1) when single-process."""
    import jax
    return jax.process_index(), jax.process_count()


def hybrid_mesh(axis_names: Tuple[str, str] = ("dcn", "ici"),
                devices: Optional[Sequence] = None):
    """A 2-D mesh [hosts(DCN) × per-host devices(ICI)].

    The outer axis crosses host boundaries (DCN-speed collectives —
    keep it for scalar reductions and rare rebalances); the inner axis
    stays within a slice (ICI-speed — shard the hot batch axes here).
    Falls back to a 1×N mesh in single-host runs, so shardings written
    against these axis names work unchanged everywhere."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    n_proc = max(jax.process_count(), 1)
    per_host = len(devs) // n_proc
    if n_proc > 1 and per_host * n_proc == len(devs):
        try:
            from jax.experimental import mesh_utils
            arr = mesh_utils.create_hybrid_device_mesh(
                (per_host,), (n_proc,), devices=devs)
            return Mesh(arr.reshape(n_proc, per_host), axis_names)
        except Exception:                               # noqa: BLE001
            pass                        # topology discovery unavailable
        # no physical topology (e.g. the CPU backend in multi-process
        # tests): group rows by owning process — that IS the host
        # boundary the outer axis models, so collectives along the
        # inner axis stay process-local wherever the runtime allows
        by_proc = sorted(devs, key=lambda d: (d.process_index, d.id))
        if (len({d.process_index for d in devs}) == n_proc
                and all(d.process_index
                        == by_proc[(i // per_host) * per_host]
                        .process_index
                        for i, d in enumerate(by_proc))):
            return Mesh(np.array(by_proc).reshape(n_proc, per_host),
                        axis_names)
    return Mesh(np.array(devs).reshape(1, len(devs)), axis_names)


class ChunkShard:
    """This process's contiguous slice of a sharded chunk axis — the
    placement contract of the multi-host chunk-lockstep path
    (:func:`reach_chunklock.walk_chunklock`): phase-B walks run
    process-local on ``chunk_range``, and :meth:`gather` is the ONE
    DCN crossing (word-packed summaries, ``all_gather`` along the
    outer axis of :func:`hybrid_mesh`)."""

    __slots__ = ("process_index", "process_count")

    def __init__(self, process_index: int, process_count: int):
        self.process_index = int(process_index)
        self.process_count = int(process_count)

    @classmethod
    def detect(cls) -> Optional["ChunkShard"]:
        """A shard for the live ``jax.distributed`` runtime, or None
        single-process (callers need no branching)."""
        idx, n = process_info()
        return cls(idx, n) if n > 1 else None

    def chunk_range(self, C: int) -> Tuple[int, int]:
        """Contiguous ``[lo, hi)`` of ``C`` chunks owned by this
        process (balanced; trailing processes may own fewer or none)."""
        per = -(-C // self.process_count)
        lo = min(self.process_index * per, C)
        return lo, min(lo + per, C)

    def gather(self, local: np.ndarray) -> np.ndarray:
        """``all_gather`` of one same-shaped array per process along
        the process axis: returns ``[process_count, *local.shape]``
        (ordered by process index). Runs the collective on a worker
        thread under :func:`gather_timeout_s` — a dead peer must cost
        bounded wall clock, not a hang — raising
        :class:`DistGatherError` on failure or deadline (the stuck
        collective thread is abandoned; it is daemonic and the caller
        proceeds with local re-derivation)."""
        box: dict = {}

        def run() -> None:
            try:
                from jax.experimental import multihost_utils
                box["out"] = np.asarray(
                    multihost_utils.process_allgather(local))
            except BaseException as e:              # noqa: BLE001
                box["err"] = e

        t = threading.Thread(target=run, daemon=True,
                             name="jepsen-dist-gather")
        t.start()
        t.join(gather_timeout_s())
        if "out" in box:
            return box["out"]
        if "err" in box:
            raise DistGatherError(
                f"all_gather failed: {box['err']!r}") from box["err"]
        raise DistGatherError(
            f"all_gather timed out after {gather_timeout_s()}s")


def keys_sharding(mesh, batch_axis: str = "ici"):
    """NamedSharding placing a leading key/chunk axis on the ICI axis of
    a :func:`hybrid_mesh` (replicated across DCN)."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(batch_axis))
