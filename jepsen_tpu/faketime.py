"""libfaketime clock lies — upstream ``jepsen.faketime`` (SURVEY.md §2.3):
start DB daemons under ``LD_PRELOAD=libfaketime`` so their clocks drift or
jump without touching the node's real clock (no root clock changes, works
alongside ntp).

Usage mirrors upstream: wrap the daemon launch::

    ctl_util.start_daemon(s, binary, ..., env=faketime.env("-30s", rate=1.1))
"""
from __future__ import annotations

from typing import Dict, Optional

from jepsen_tpu.control import Session

# common soname locations, era-dependent across distros
_LIBS = ("/usr/lib/x86_64-linux-gnu/faketime/libfaketime.so.1",
         "/usr/lib/faketime/libfaketime.so.1",
         "/usr/lib64/faketime/libfaketime.so.1")


def install(s: Session) -> None:
    """Install the faketime package on a node (upstream installs via apt)."""
    s = s.su()
    if s.exec_raw("which faketime").exit_code != 0:
        s.exec_raw("apt-get -qy install faketime || "
                   "yum -y -q install libfaketime || true")


def lib_path(s: Session) -> Optional[str]:
    for p in _LIBS:
        if s.exec_raw(f"test -e {p}").exit_code == 0:
            return p
    out = s.exec_raw(
        "find /usr/lib* -name 'libfaketime.so*' 2>/dev/null | head -1")
    return out.out.strip() or None


def env(offset: str = "+0s", rate: Optional[float] = None,
        lib: str = _LIBS[0]) -> Dict[str, str]:
    """Environment for a faketime'd daemon: ``offset`` like ``"-30s"`` /
    ``"+2h"``; ``rate`` speeds up or slows down the clock (upstream
    ``faketime/jvm-opts``-style ``x`` rates)."""
    spec = offset if rate is None else f"{offset} x{rate}"
    return {"LD_PRELOAD": lib, "FAKETIME": spec,
            "FAKETIME_NO_CACHE": "1"}


def wrap(cmd: str, offset: str = "+0s", rate: Optional[float] = None) -> str:
    """Prefix a shell command with the faketime CLI (simpler alternative
    when the binary is available)."""
    spec = offset if rate is None else f"{offset} x{rate}"
    return f"faketime -f {spec!r} {cmd}"
