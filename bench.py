"""Benchmark entry point — run the BASELINE.md ladder's headline config and
print ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline (BASELINE.json north star): verify a 100k-op CAS-register history
for linearizability in <60 s on TPU; metric is ops verified per second, and
``vs_baseline`` is measured throughput over the north-star floor
(100_000 ops / 60 s ≈ 1667 ops/s). The reference publishes no numbers of its
own (SURVEY.md §6) — CPU Knossos folklore is that 100k-op single-key
histories simply time out.

Usage: python bench.py [--ops N] [--repeat K] [--engine reach|chunked]
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=100_000)
    ap.add_argument("--processes", type=int, default=5)
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--engine", default="reach",
                    choices=["reach", "chunked", "wgl-cpu", "wgl-native"])
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="write a jax.profiler trace of one steady-state "
                         "check to DIR")
    args = ap.parse_args()

    from jepsen_tpu import fixtures, models
    from jepsen_tpu.checkers import reach, wgl_ref
    from jepsen_tpu.history import pack

    t0 = time.monotonic()
    history = fixtures.gen_history("cas", n_ops=args.ops,
                                   processes=args.processes, seed=args.seed)
    gen_s = time.monotonic() - t0
    model = models.cas_register()
    packed = pack(history)

    def run():
        if args.engine == "reach":
            return reach.check_packed(model, packed)
        if args.engine == "chunked":
            return reach.check_chunked(model, packed=packed)
        if args.engine == "wgl-native":
            from jepsen_tpu.checkers import wgl_native
            return wgl_native.check_packed(model, packed)
        return wgl_ref.check_packed(model, packed, time_limit=300)

    # warm-up: first call pays jit compilation; the measurement is steady
    # state (compile caches persist across runs of the same shapes).
    res = run()
    if res["valid"] is not True:
        print(json.dumps({"metric": "linearize-100k-cas",
                          "value": 0.0, "unit": "ops/s",
                          "vs_baseline": 0.0,
                          "error": f"bad verdict {res.get('valid')}"}))
        return 1
    times = []
    if args.profile:
        # SURVEY.md §5 tracing: a jax.profiler trace of the steady-state
        # solver, viewable in TensorBoard / Perfetto
        import jax
        with jax.profiler.trace(args.profile):
            t1 = time.monotonic()
            res = run()
            times.append(time.monotonic() - t1)
    for _ in range(max(1, args.repeat)):
        t1 = time.monotonic()
        res = run()
        times.append(time.monotonic() - t1)
    best = min(times)
    ops_per_s = args.ops / best
    baseline_floor = 100_000 / 60.0
    out = {
        "metric": f"linearize-{args.ops // 1000}k-cas",
        "value": round(ops_per_s, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_s / baseline_floor, 2),
        "check_s": round(best, 3),
        "gen_s": round(gen_s, 2),
        "engine": res.get("engine"),
        "valid": res.get("valid"),
        "events": res.get("events"),
        "slots": res.get("slots"),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
