"""Benchmark entry point — run the BASELINE.md ladder's headline config and
print ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline (BASELINE.json north star): verify a 100k-op CAS-register history
for linearizability in <60 s on TPU; metric is ops verified per second, and
``vs_baseline`` is measured throughput over the north-star floor
(100_000 ops / 60 s ≈ 1667 ops/s). The reference publishes no numbers of its
own (SURVEY.md §6) — CPU Knossos folklore is that 100k-op single-key
histories simply time out.

With ``--engine reach`` (the default) the run also reports a
kernel-level probe (SURVEY.md §5 tracing): steady-state device time of
the lane kernel separated from host->device transfer and the
dispatch/fetch round-trip, plus an honest MFU figure. The probe drives
the PRODUCTION dispatch path (``reach_lane._pipe_walk`` — the same
segmented programs ``check_packed`` runs) and times the kernel by
dispatch slope (K queued walks + one fetch, minus a single walk +
fetch) because ``block_until_ready`` does not block on the tunneled
dev platform. The bare round-trip latency is sampled separately
(min of several dispatch+fetch cycles of a jitted scalar reduction
over the already-resident operand set — the same observer the
transfer measurement pays) and subtracted from the transfer figure,
so ``transfer_sync_s`` is bytes on the wire, not latency; raw
put+observe = ``transfer_sync_s + rtt_s``.

The default run's ``"batch"`` sub-object carries the lockstep batch
rung (``reach.check_batch``) with its bucketed-dispatch diagnostics:
per-bucket geometry (``per_bucket``: H/B/W/S/R_pad and real vs padded
returns per lockstep group), ``pack_efficiency`` (real returns over
padded lockstep steps — the win of length-bucketed lane packing),
``kernel_cache`` (hit/miss counters of the per-geometry compiled-kernel
cache), the mesh scaling story (``n_devices``, ``per_device_groups``,
``mesh_pad_lanes`` — 1/None/0 on single-device runs), and aggregate
ops/s. ``--engine batch`` promotes the batch
dimension to the HEADLINE: a ragged independent-keys workload
(BASELINE config #4 shape — ``--ops`` total over ≥8 keys of mixed
lengths) through ``reach.check_many``'s bucketed lockstep lane,
reported against the sequential per-key baseline measured in the same
run. All of it lands in the BENCH_*.json trajectory artifacts.

Every run also emits an ``"obs"`` sub-object — the
:mod:`jepsen_tpu.obs` snapshot taken over the run: the engine-decision
ledger (which engine the measured check selected, every fallback with
its cause), the cache/fallback counters (``reach.pallas_fallback``,
``lockstep.kernel_cache.*``, ``lockstep.transfer_bytes``, pack
efficiency), and the span count — and writes a Chrome/Perfetto
``trace.json`` (``--trace PATH``, empty string disables) that
``tools/trace_view.py`` summarizes.

``--serve`` appends a ``"serve"`` sub-object: an in-process
checker-as-a-service daemon (ISSUE 6) driven by the open-loop load
generator (``tools/loadgen.py``) — sustained req/s, p50/p99 verdict
latency across two measurement windows (the second runs entirely on
warm caches), backpressure/timeout counts, and the daemon's final
``serve.*`` counter snapshot — plus a ``"session"`` sub-object (ISSUE
11): the streaming-session rung, sustained append ops/s and p50/p99
append-to-verdict latency of the device-resident carried-frontier
engine vs the host ``OnlineLinearizable`` monitor at its production
flush cadence, with the jax ``platform`` named so the device-vs-host
comparison reads honestly on CPU-only runs — and a ``"session_mux"``
sub-object (ISSUE 16): L live same-geometry streams advanced through
ONE vmapped mega-batch launch per wave vs L per-session launches, at
several lane widths up to 5000 sessions, appends/s and p99 both ways
with the measured crossover persisted to the autotune table.

Usage: python bench.py [--ops N] [--repeat K]
       [--engine reach|chunked|batch|wgl-cpu|wgl-native]
       [--trace trace.json] [--serve]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


# peak dense bf16 MXU throughput of one TPU v5-lite chip, for the MFU
# denominator (the walk is latency-bound tiny-matmul work, so MFU is
# honestly tiny — the point of reporting it)
_PEAK_FLOPS = 197e12


def _lane_operands(model, packed):
    """The single-history lane operand set every probe measures: memo
    BFS + union transition tensor + the PRODUCTION packing
    (``reach_lane.pack_operands``). Shared so one bench run pays this
    host prep once for both ``transfer_probe`` and ``kernel_probe``.
    Returns ``(rs, geom, host_args, p_nbytes)``."""
    import numpy as np

    from jepsen_tpu.checkers import events as ev
    from jepsen_tpu.checkers import reach, reach_lane

    memo, stream, _T, S, M = reach._prep(
        model, packed, max_states=100_000, max_slots=20,
        max_dense=1 << 22)
    rs = ev.returns_view(stream)
    P_np = reach._build_P(memo, S)
    R0 = np.zeros((S, M), bool)
    R0[0, 0] = True
    geom, _, _, host_args = reach_lane.pack_operands(
        P_np, rs.ret_slot, rs.slot_ops, R0)
    return rs, geom, host_args, int(P_np.nbytes)


def _pallas_needs_accelerator() -> bool:
    """True when compiled-Pallas probes cannot run on this backend
    (CPU only supports interpret mode, whose timings would mislead)."""
    try:
        import jax
        return jax.default_backend() == "cpu"
    except Exception:                                   # noqa: BLE001
        return True


def kernel_probe(model, packed, prep=None, prep_s=None) -> dict:
    """Steady-state device-kernel probe for the single-history lane
    walk: returns kernel_s (dispatch-slope), transfer_s / bytes, the
    dispatch+fetch round-trip, and MFU. Raises if the lane path does
    not admit the history (caller treats the probe as best-effort).
    ``prep``/``prep_s`` carry a pre-built :func:`_lane_operands` set
    (and its measured wall) so a full bench run preps once."""
    import numpy as np

    import jax
    from jepsen_tpu.checkers import reach_lane

    if prep is None:
        t_prep = time.monotonic()
        prep = _lane_operands(model, packed)
        prep_s = time.monotonic() - t_prep
    # marshaling AND dispatch shared with the production path — the
    # probe runs reach_lane._pipe_walk itself, so it can never time a
    # kernel or a pipeline production does not execute
    rs, geom, host_args, p_nbytes = prep
    R_real = int(rs.ret_slot.shape[0])
    B, W, M, S, O1, R_pad = geom
    n_pass = min(W, reach_lane._FAST_PASSES)
    from jepsen_tpu.checkers import transfer as xfer

    # the put-observer moves the TRUE production wire: the dominant
    # slot_ops lane crosses 6-bit packed PER SEGMENT (exactly what
    # _pipe_walk uploads, ragged-tail pad included), so transfer_sync_s
    # and the reported bytes describe the same transfer — the diet, not
    # the pre-pack host staging arrays
    _rs_w, _so_w, _P_w, _r0_w = host_args
    if xfer.packed_enabled() and xfer.sextet_ok(O1):
        wire_args = (_rs_w, reach_lane.pack_ops_wire(geom, _so_w),
                     _P_w, _r0_w)
    else:
        wire_args = host_args
    n_bytes = reach_lane.wire_bytes(geom, host_args)

    # the probe's verdict fetch matches the production protocol: lazy
    # (the default) crosses ONE on-device-reduced boolean, eager the
    # full [M, S] final set — so dispatch_fetch_s reflects the diet
    if xfer.lazy_fetch_enabled():
        def verdict_fetch(fin):
            return bool(np.asarray(reach_lane._jit_any()(fin)))
    else:
        def verdict_fetch(fin):
            return np.asarray(fin)
    dsegs: dict = {}
    _, final = reach_lane._pipe_walk(host_args, geom, n_pass, False,
                                     dsegs)
    _ = verdict_fetch(final)                    # warm/compile
    # put-completion observer: a scalar reduction CONSUMING every
    # operand, jitted once. Fetching a put array back is free (jax
    # keeps the committed host copy), so observing transfer completion
    # requires a device computation that depends on the bytes.
    import jax.numpy as jnp
    observe = jax.jit(lambda a, b, c, d: (
        a.astype(jnp.int32).sum() + b.astype(jnp.int32).sum()
        + c.sum().astype(jnp.int32) + d.sum().astype(jnp.int32)))
    args2 = jax.device_put(wire_args)
    _ = int(observe(*args2))                    # warm/compile
    # bare dispatch+fetch round trip on RESIDENT operands — the latency
    # floor every sync pays regardless of bytes moved (min of several
    # samples: single-shot jitter is the same order as the transfer)
    rtts = []
    for _i in range(4):
        t0 = time.monotonic()
        _ = int(observe(*args2))
        rtts.append(time.monotonic() - t0)
    rtt_s = min(rtts)
    # transfer: one put of the full operand set, forced to completion
    # by the observer; the observer's own dispatch+fetch is latency,
    # not transfer, so the sampled floor is subtracted. Raw
    # put+observe = transfer_sync_s + rtt_s.
    t0 = time.monotonic()
    args2 = jax.device_put(wire_args)
    _ = int(observe(*args2))
    transfer_s = max(0.0, time.monotonic() - t0 - rtt_s)
    put_s = transfer_s
    # steady-state walk split into its pipeline stages: dispatch_s is
    # the host time to queue every device program, fetch_s the
    # verdict round-trip — together with prep_s these attribute the
    # ~47 ms of check_s the kernel slope leaves unexplained, so the
    # overlap win is measurable run-over-run
    t0 = time.monotonic()
    _, final = reach_lane._pipe_walk(host_args, geom, n_pass, False,
                                     dsegs)
    t1 = time.monotonic()
    _ = verdict_fetch(final)
    t2 = time.monotonic()
    dispatch_only_s = t1 - t0
    fetch_s = t2 - t1
    one_s = t2 - t0                       # 1 walk (dispatches) + fetch
    K = 6
    t0 = time.monotonic()
    for _i in range(K):
        _, final = reach_lane._pipe_walk(host_args, geom, n_pass, False,
                                         dsegs)
    _ = verdict_fetch(final)
    many_s = time.monotonic() - t0
    kernel_s = max(0.0, (many_s - one_s) / (K - 1))
    # FLOPs: min(c_r, n_pass) fire matmuls [M,S]@[S,W*S] per return —
    # the gate ladder executes exactly the pending-count bound (the VPU
    # reshuffles and projection move bytes, not FLOPs)
    executed = np.minimum(
        (rs.slot_ops >= 0).sum(axis=1), n_pass).sum()
    flops = 2.0 * M * S * W * S * float(executed)
    # transfer-diet breakdown: actual bytes on the wire (narrow ints +
    # bit-packed bools) vs the blanket int32/f32 format, and which
    # fetch protocol the verdict crossed on — the run-over-run evidence
    # the CI transfer-guard budgets
    unpacked_bytes = reach_lane.blanket_bytes(geom, p_nbytes)
    return {
        "kernel_s": round(kernel_s, 4),
        "kernel_ns_per_return": round(kernel_s / max(R_real, 1) * 1e9),
        "returns": R_real,
        "transfer_sync_s": round(transfer_s, 4),
        "transfer_bytes": int(n_bytes),
        # put_s/packed_bytes alias the two fields above under the
        # round-6 names the transfer tooling reads; the round-5 names
        # stay so BENCH_r01-r05 comparisons keep working
        "put_s": round(put_s, 4),
        "packed_bytes": int(n_bytes),
        "unpacked_bytes": int(unpacked_bytes),
        "fetch_mode": xfer.fetch_mode(),
        "rtt_s": round(rtt_s, 4),
        "dispatch_fetch_s": round(one_s - kernel_s, 4),
        "prep_s": round(prep_s, 4),
        "dispatch_s": round(dispatch_only_s, 4),
        "fetch_s": round(fetch_s, 4),
        "mfu_pct": round(flops / max(kernel_s, 1e-9) / _PEAK_FLOPS * 100,
                         4),
    }


def transfer_probe(model, packed, prep=None) -> dict:
    """Host-only marshalling breakdown of the single-history wire
    format: runs the PRODUCTION operand packing
    (``reach_lane.pack_operands`` — no device dispatch, so this works
    on CPU-only CI) and reports actual vs blanket-int32/f32 bytes.
    The ``transfer-guard`` CI step budgets these numbers so a wire
    regression (a re-widened dtype, an unpacked bool tensor) fails the
    build. ``prep`` reuses a :func:`_lane_operands` set."""
    from jepsen_tpu.checkers import reach_lane
    from jepsen_tpu.checkers import transfer as xfer

    if prep is None:
        prep = _lane_operands(model, packed)
    rs, geom, host_args, p_nbytes = prep
    # reach_lane.wire_bytes is the production accounting — it includes
    # the per-segment 6-bit packing of the dominant slot_ops lane that
    # _pipe_walk applies at upload time, so the guard budgets what the
    # link actually carries
    packed_bytes = int(reach_lane.wire_bytes(geom, host_args))
    unpacked_bytes = int(reach_lane.blanket_bytes(geom, p_nbytes))
    round5_bytes = int(reach_lane.round5_bytes(geom, p_nbytes))
    return {
        "returns": int(rs.n_returns),
        "packed_bytes": packed_bytes,
        "unpacked_bytes": unpacked_bytes,
        # ratio is vs the dtype-blind blanket reference the guard
        # budgets; vs_round5 is vs the narrow wire round 5 actually
        # shipped (upload side only — the fetch-side win is separate)
        "ratio": round(unpacked_bytes / max(packed_bytes, 1), 2),
        "round5_bytes": round5_bytes,
        "vs_round5": round(round5_bytes / max(packed_bytes, 1), 2),
        "bytes_per_return": round(
            packed_bytes / max(int(rs.n_returns), 1), 2),
        "fetch_mode": xfer.fetch_mode(),
        "gates": {"packed": xfer.packed_enabled(),
                  "lazy_fetch": xfer.lazy_fetch_enabled(),
                  "donate": xfer.donate_enabled()},
    }


def chunklock_probe(model, packed) -> dict:
    """Steady-state timing of the chunk-lockstep walk — the production
    single-history engine at the headline rung (round-5): warm best-of
    e2e of the full phase-A/glue/phase-B/fold dispatch chain, plus its
    geometry diagnostics."""
    import time as _t

    from jepsen_tpu.checkers import events as ev
    from jepsen_tpu.checkers import reach
    from jepsen_tpu.checkers import reach_chunklock as rcl

    memo, stream, _T, S, M = reach._prep(
        model, packed, max_states=100_000, max_slots=20,
        max_dense=1 << 22)
    rs = ev.returns_view(stream)
    if not rcl.admits(S, M, max(stream.W, 1), rs.n_returns):
        return {"skipped": "outside chunklock envelope"}
    P = reach._build_P(memo, S)
    dead, diag = rcl.walk_chunklock(P, rs.ret_slot, rs.slot_ops, M)
    times = []
    for _ in range(4):
        t0 = _t.monotonic()
        dead, diag = rcl.walk_chunklock(P, rs.ret_slot, rs.slot_ops, M)
        times.append(_t.monotonic() - t0)
    best = min(times)
    return {"walk_s": round(best, 4),
            "ns_per_return": round(best / max(rs.n_returns, 1) * 1e9),
            "returns": int(rs.n_returns), "dead": int(dead), **diag}


def batch_probe(model, n_ops: int, seed: int, processes: int) -> dict:
    """Lockstep batch rung (BASELINE.md round-4): H independent
    histories through ONE ``reach.check_batch`` call — the batch axis
    is where the device wins end-to-end, so the official bench
    artifact carries its aggregate throughput alongside the
    single-history headline. Warm best-of-2 e2e (includes union prep
    and marshaling — the honest user cost)."""
    from jepsen_tpu import fixtures
    from jepsen_tpu.checkers import reach

    H = reach._BATCH_GROUP
    packeds = [fixtures.gen_packed("cas", n_ops=n_ops,
                                   processes=processes,
                                   seed=seed + 1000 + i)
               for i in range(H)]
    diag: dict = {}
    res = reach.check_batch(model, packeds, diag=diag)  # warm/compile
    if not all(r["valid"] is True for r in res):
        return {"error": "bad batch verdicts"}
    engines = {r["engine"] for r in res}
    if engines != {"reach-lockstep"}:
        # the lockstep gates did not hold (CPU-only run, no native
        # lib, ...) and check_batch fell back to sequential
        # per-history checks — timing that as "the batch rung" would
        # mislabel sequential throughput, so skip like kernel_probe
        return {"skipped": f"no lockstep path ({sorted(engines)})"}
    times = []
    best_diag = diag
    for _ in range(2):
        d: dict = {}
        t1 = time.monotonic()
        reach.check_batch(model, packeds, diag=d)
        dt = time.monotonic() - t1
        if not times or dt < min(times):
            best_diag = d or diag
        times.append(dt)
    best = min(times)
    prep = best_diag.get("prep", {})
    mesh = best_diag.get("mesh") or {}
    return {"H": H, "e2e_s": round(best, 3),
            "agg_ops_s": round(H * n_ops / best),
            "engine": sorted(engines),
            # mesh scaling story (single-device runs report 1 device,
            # no per-device split): device count, groups walked per
            # device, and the lane-pad waste of sharding
            "n_devices": mesh.get("n_devices", 1),
            "per_device_groups": mesh.get("per_device_groups"),
            "mesh_pad_lanes": mesh.get("pad_lanes", 0),
            # prep/dispatch/fetch attribution of the best e2e run —
            # prep_hidden_s / prep_s is the streaming overlap win
            "prep_s": prep.get("wall_s"),
            "prep_hidden_s": prep.get("hidden_s"),
            "prep_mode": prep.get("mode"),
            "dispatch_s": best_diag.get("dispatch_s"),
            "fetch_s": best_diag.get("fetch_s"),
            # transfer-diet evidence: wire bytes under the diet vs the
            # blanket format, and the verdict fetch protocol
            "transfer": best_diag.get("transfer"),
            "pack_efficiency": best_diag.get("pack_efficiency"),
            "real_returns": best_diag.get("real_returns"),
            "padded_returns": best_diag.get("padded_returns"),
            "kernel_cache": best_diag.get("kernel_cache"),
            "per_bucket": best_diag.get("groups", [])}


def serve_probe(quick: bool = True) -> dict:
    """The serving-layer rung: self-host a daemon on an ephemeral
    port, replay a mixed-geometry multi-tenant workload at a target
    arrival rate through ``tools/loadgen.py``, and report sustained
    req/s + p50/p99 verdict latency (two windows: the second is the
    steady state a long-lived daemon lives in)."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "loadgen.py")
    spec = importlib.util.spec_from_file_location("bench_loadgen",
                                                  path)
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)
    report = loadgen.run_loadgen({"quick": quick,
                                  "find_capacity": True})
    # the full per-request record set is loadgen's business; keep the
    # bench artifact to the headline numbers + the daemon's counters
    keep = ("warmup", "target_rate", "duration_s", "submitted",
            "completed", "rejected_429", "timeouts",
            "verdict_mismatches", "sustained_req_s", "saturated",
            "capacity", "p50_s",
            "p99_s", "p50_admit_s", "p99_admit_s", "windows",
            "stage_split", "latency_crosscheck",
            "fallbacks", "drained", "error")
    out = {k: report[k] for k in keep if k in report}
    stats = report.get("stats", {})
    out["counters"] = {k: v
                       for k, v in stats.get("counters", {}).items()
                       if k.startswith(("serve.", "pipeline."))}
    out["dispatch"] = stats.get("dispatch", {})
    # the daemon's histogram-derived tails + padding waste: the
    # serving-quality numbers BENCH_r*.json tracks across PRs
    out["histograms"] = stats.get("histograms", {})
    out["pad_waste_s"] = stats.get("counters", {}).get(
        "serve.pad_waste_s")
    out["device_s"] = stats.get("counters", {}).get("serve.device_s")
    # the fleet rung (ISSUE 15): two replica daemons over ONE shared
    # store root, loadgen round-robin across both, scaling efficiency
    # against the single-daemon sustained rate measured above
    try:
        out["fleet"] = _fleet_serve_probe(
            loadgen, baseline=out.get("sustained_req_s"), quick=quick)
    except Exception as e:                              # noqa: BLE001
        out["fleet"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _fleet_serve_probe(loadgen, *, baseline, quick=True) -> dict:
    """Spawn 2 ``check-serve`` replica subprocesses over one store
    root (reusing the chaos harness's process manager), drive
    loadgen's client-side round-robin at them, and report the merged
    throughput + scaling efficiency + per-replica lease counters
    (claims prove the shared-journal partition actually engaged)."""
    import importlib.util
    import os
    import shutil
    import tempfile

    cpath = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "chaos.py")
    spec = importlib.util.spec_from_file_location("bench_chaos", cpath)
    chaos = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chaos)
    root = tempfile.mkdtemp(prefix="bench-fleet-")
    procs = [chaos.DaemonProc(
        root, faults_env="",
        log_path=os.path.join(root, f"r{i}.log"),
        extra_args=["--replica-id", f"r{i}",
                    "--lease-ttl", "10.0", "--lanes", "2"])
        for i in range(2)]
    try:
        rep = loadgen.run_loadgen({
            "quick": quick,
            "replicas": [p.url for p in procs],
            "baseline_req_s": baseline})
        fleet = dict(rep.get("fleet") or {})
        for k in ("sustained_req_s", "p50_s", "p99_s", "submitted",
                  "completed", "verdict_mismatches", "error"):
            if rep.get(k) is not None:
                fleet[k] = rep[k]
        leases = {}
        for i, p in enumerate(procs):
            code, st = loadgen._get(p.url, "/stats")
            if code == 200:
                leases[f"r{i}"] = {
                    k: v for k, v in st.get("counters", {}).items()
                    if k.startswith("serve.lease.")}
        fleet["lease_counters"] = leases
        return fleet
    finally:
        for p in procs:
            try:
                p.sigterm()
            except Exception:                           # noqa: BLE001
                try:
                    p.sigkill()
                except Exception:                       # noqa: BLE001
                    pass
        shutil.rmtree(root, ignore_errors=True)


def session_probe(n_ops: int = 100_000, seed: int = 42,
                  block: int = 4096, quick: bool = False) -> dict:
    """The streaming-session rung (ISSUE 11): one cas op stream fed
    twice — once through the device-resident session engine
    (``serve.session.Session``: carried frontier advanced in place
    per append block, donated buffers) and once through the host
    ``OnlineLinearizable`` monitor at its production flush cadence —
    reporting sustained append ops/s and the p50/p99
    append-to-verdict latency for both. ``platform`` names the jax
    backend the session walk actually ran on: the device-resident
    path exists to beat the host monitor where there IS a device
    (the post-hoc walk does 8.9M ops/s there); on a CPU-only jax the
    same XLA program is thunk-overhead-bound and the C++ host monitor
    keeps the crown — the honest number either way."""
    import jax

    from jepsen_tpu import fixtures, models
    from jepsen_tpu.checkers import online
    from jepsen_tpu.serve.session import Session

    if quick:
        n_ops = min(n_ops, 20_000)
    hist = fixtures.gen_history("cas", n_ops=n_ops, processes=5,
                                seed=seed)
    model = models.cas_register()
    blocks = [hist[i:i + block] for i in range(0, len(hist), block)]

    def drive_session() -> dict:
        s = Session("bench", "bench", "cas-register", model)
        lats = []
        t0 = time.monotonic()
        verdict = True
        for i, b in enumerate(blocks):
            t1 = time.monotonic()
            r = s.advance_block(b, seq=i + 1)
            lats.append(time.monotonic() - t1)
            verdict = verdict and r["valid-so-far"]
        wall = time.monotonic() - t0
        lats.sort()
        return {"wall_s": round(wall, 3),
                "ops_s": round(len(hist) / wall),
                "engine": s.engine_name,
                "valid": verdict,
                "appends": len(blocks),
                "append_p50_s": round(lats[len(lats) // 2], 4),
                "append_p99_s": round(
                    lats[min(len(lats) - 1,
                             int(len(lats) * 0.99))], 4)}

    def drive_host() -> dict:
        mon = online.OnlineLinearizable(model)
        lats = []
        t0 = time.monotonic()
        n = 0
        for op in hist:
            mon.observe(op)
            n += 1
            if n % 256 == 0:        # the monitor's production cadence
                t1 = time.monotonic()
                mon.flush()
                lats.append(time.monotonic() - t1)
        res = mon.stop()
        wall = time.monotonic() - t0
        lats.sort()
        return {"wall_s": round(wall, 3),
                "ops_s": round(len(hist) / wall),
                "engine": ("online-native"
                           if type(mon._engine).__name__
                           == "NativeStreamEngine" else "online-py"),
                "valid": res.get("valid"),
                "flush_p50_s": (round(lats[len(lats) // 2], 5)
                                if lats else None),
                "flush_p99_s": (round(
                    lats[min(len(lats) - 1,
                             int(len(lats) * 0.99))], 5)
                    if lats else None)}

    sess_cold = drive_session()     # compile wall included
    sess_warm = drive_session()     # the steady state a daemon lives in
    host = drive_host()
    out = {
        "ops": len(hist), "block": block,
        "platform": jax.default_backend(),
        "session": sess_warm,
        "session_cold": sess_cold,
        "host_monitor": host,
        "session_vs_host": round(
            sess_warm["ops_s"] / max(host["ops_s"], 1), 3),
        "beats_host": sess_warm["ops_s"] > host["ops_s"],
    }
    if sess_warm["valid"] is not True or host["valid"] is not True:
        out["error"] = (f"verdict drift: session "
                        f"{sess_warm['valid']} host {host['valid']}")
    return out


def session_mux_probe(widths=(8, 64, 512, 5000), waves: int = 6,
                      quick: bool = False) -> dict:
    """The session-multiplexing rung (ISSUE 16): L live streams of
    identical walk geometry advanced one wave at a time, first
    member-by-member (L launches per wave — the pre-mux daemon) and
    then through ``session.advance_group`` (ONE vmapped launch per
    wave), at several lane widths. Streams use a closed two-value
    alphabet so the geometry never regrows and every lane stays in
    the group — the pure dispatch-amortization number, no coalescer
    noise. Reports appends/s and p99 append-to-verdict both ways per
    width (a batched append's latency is its wave's wall — the
    append is not done until its launch lands), and persists the
    measured crossover (the smallest width where the batch wins) in
    the autotune table for ``session.mega_crossover``."""
    import jax

    from jepsen_tpu import models
    from jepsen_tpu.checkers import autotune
    from jepsen_tpu.op import invoke, ok
    from jepsen_tpu.serve import session as sessmod
    from jepsen_tpu.serve.session import Session

    if quick:
        widths = tuple(w for w in widths if w <= 64) or (8, 64)
        waves = 3
    b1 = [invoke(0, "write", 1), ok(0, "write", 1),
          invoke(1, "read"), ok(1, "read", 1),
          invoke(0, "write", 2), ok(0, "write", 2),
          invoke(1, "read"), ok(1, "read", 2)]
    bw = [invoke(1, "write", 1), ok(1, "write", 1),
          invoke(0, "read"), ok(0, "read", 1),
          invoke(0, "write", 2), ok(0, "write", 2),
          invoke(1, "read"), ok(1, "read", 2)]
    model = models.register()

    def seed_sessions(prefix: str, n: int):
        ss = [Session(f"{prefix}{i}", f"t{i % 8}", "register", model)
              for i in range(n)]
        for s in ss:                    # solo seed: signatures align
            s.advance_block(b1, seq=1)
        return ss

    def drive(n: int, grouped: bool) -> dict:
        ss = seed_sessions("mega" if grouped else "solo", n)
        lats = []
        t0 = time.monotonic()
        valid = True
        for w in range(waves):
            entries = [(s, list(bw), w + 2) for s in ss]
            # every lane's append "arrives" at the wave's cadence
            # tick, so an append's latency runs from wave start to
            # ITS verdict: the batched members all land with the
            # launch; the per-session members queue behind their
            # predecessors on the one dispatcher — the real shape
            # mux replaces
            t1 = time.monotonic()
            if grouped:
                # force: a previously persisted session-mega
                # crossover must not silently re-route small widths
                # to the per-session path mid-measurement
                rs = sessmod.advance_group(entries, force=True)
                lats.extend([time.monotonic() - t1] * n)
            else:
                for s, b, q in entries:
                    r = s.advance_block(b, seq=q)
                    lats.append(time.monotonic() - t1)
                    valid = valid and r["valid-so-far"]
                rs = []
            valid = valid and all(r["valid-so-far"] for r in rs)
        wall = time.monotonic() - t0
        lats.sort()
        return {"wall_s": round(wall, 3),
                "appends_s": round(n * waves / wall),
                "valid": valid,
                "append_p99_s": round(
                    lats[min(len(lats) - 1,
                             int(len(lats) * 0.99))], 5)}

    out: dict = {"platform": jax.default_backend(), "waves": waves,
                 "block_ops": len(bw), "widths": {}}
    crossover = None
    for n in widths:
        solo = drive(n, grouped=False)
        mega_cold = drive(n, grouped=True)   # compile wall included
        mega = drive(n, grouped=True)        # the daemon steady state
        ratio = round(mega["appends_s"] / max(solo["appends_s"], 1),
                      2)
        out["widths"][str(n)] = {
            "per_session": solo, "mega": mega,
            "mega_cold_wall_s": mega_cold["wall_s"],
            "speedup": ratio,
            "p99_not_worse": (mega["append_p99_s"]
                              <= solo["append_p99_s"]),
        }
        if not (solo["valid"] and mega["valid"]):
            out["error"] = f"verdict drift at width {n}"
        if crossover is None and ratio > 1.0:
            crossover = n
    out["headline"] = out["widths"][str(max(widths))]
    if crossover is not None:
        out["crossover"] = crossover
        out["recorded"] = autotune.record(
            "session-mega", "crossover", str(crossover),
            metric=out["headline"]["speedup"],
            detail={"widths": list(widths), "waves": waves})
    return out


def txn_probe(n_txns: int, seed: int) -> dict:
    """The transactional rung (ISSUE 9): a ``n_txns`` list-append
    history (key-rotated, the real Jepsen workload shape) with one
    injected G-single block, classified end-to-end — dependency
    inference + the MXU boolean-closure engine vs the host SCC
    baseline on the SAME inferred graph. Reports agg txns/s both ways
    (warm best-of-2), the Kahn-trimmed core size the dense closure
    actually walked, and the detected anomaly classes (the injected
    class must be among them, or the rung reports an error)."""
    from jepsen_tpu import fixtures, txn
    from jepsen_tpu.txn import infer as txn_infer
    from jepsen_tpu.txn import ops as txn_ops

    t0 = time.monotonic()
    h = fixtures.gen_txn_history(n_txns, keys=6, processes=8,
                                 key_rotate=32, seed=seed)
    h = h + [op.with_(index=-1) for op in
             fixtures.txn_anomaly_block("G-single")]
    # index ONCE at composition (the anomaly block rides in with
    # index=-1): production histories arrive indexed — re-indexing
    # 2*n ops inside every timed check_history call was measuring
    # history construction, not checking
    from jepsen_tpu import history as h_mod
    h = h_mod.index(h)
    gen_s = time.monotonic() - t0
    t0 = time.monotonic()
    txns, fails = txn_ops.collect(h)
    graph = txn_infer.infer(txns, fails)
    infer_s = time.monotonic() - t0

    def best_of(fn, k=2):
        res, times = None, []
        for _ in range(k):
            t1 = time.monotonic()
            res = fn()
            times.append(time.monotonic() - t1)
        return res, min(times)

    from jepsen_tpu.txn import cycles as txn_cycles

    # the dev arm measures the SHIPPING DEFAULT body (word unless the
    # opt-out is set): bypass the autotune table so a recorded "f32"
    # winner can't silently swap the body under the "word" label below
    os.environ["JEPSEN_TPU_NO_AUTOTUNE"] = "1"
    try:
        dev, dev_s = best_of(lambda: txn.check_history(h))
        os.environ["JEPSEN_TPU_NO_WORD_CLOSURE"] = "1"
        try:
            f32, f32_s = best_of(lambda: txn.check_history(h))
        finally:
            os.environ.pop("JEPSEN_TPU_NO_WORD_CLOSURE", None)
    finally:
        os.environ.pop("JEPSEN_TPU_NO_AUTOTUNE", None)
    host, host_s = best_of(
        lambda: txn.check_history(h, force_host=True))
    # the lattice rung (ISSUE 17): every consistency level decided in
    # ONE dispatch — the K=4 ladder vs the host chain-node lattice
    # reference. (Not apples-to-apples with the serializable arm:
    # the lattice route never rides the Kahn trim, so it walks the
    # full graph where dev walks the trimmed core.)
    from jepsen_tpu.txn import lattice as txn_lattice
    all_levels = list(txn_lattice.LEVELS)
    lat, lat_s = best_of(
        lambda: txn.check_history(h, consistency=all_levels))
    lat_host, lat_host_s = best_of(
        lambda: txn.check_history(h, consistency=all_levels,
                                  force_host=True))
    out = {
        "txns": int(graph.n), "edges": int(graph.e),
        "edge_counts": graph.edge_counts(),
        "gen_s": round(gen_s, 2), "infer_s": round(infer_s, 2),
        "device": {"check_s": round(dev_s, 3),
                   "txns_s": round(graph.n / max(dev_s, 1e-9)),
                   "engine": dev.get("engine"),
                   "body": ("word" if txn_cycles.word_closure_enabled()
                            else "f32"),
                   "core_txns": dev.get("core-txns"),
                   "anomalies": dev.get("anomalies")},
        "device_f32": {"check_s": round(f32_s, 3),
                       "txns_s": round(graph.n / max(f32_s, 1e-9)),
                       "anomalies": f32.get("anomalies")},
        "host": {"check_s": round(host_s, 3),
                 "txns_s": round(graph.n / max(host_s, 1e-9)),
                 "engine": host.get("engine"),
                 "anomalies": host.get("anomalies")},
        "speedup_vs_host": round(host_s / max(dev_s, 1e-9), 2),
        "lattice": {
            "check_s": round(lat_s, 3),
            "txns_s": round(graph.n / max(lat_s, 1e-9)),
            "engine": lat.get("engine"),
            "weakest_violated": lat.get("weakest-violated"),
            "host_check_s": round(lat_host_s, 3),
            "speedup_vs_host": round(lat_host_s / max(lat_s, 1e-9),
                                     2),
            "cost_vs_serializable": round(lat_s / max(dev_s, 1e-9),
                                          2)},
        # the closure KERNEL in isolation: the e2e rung above trims
        # to a tiny core (inference dominates), so the body win is
        # measured on a closure-bound synthetic cyclic graph too,
        # and the winner lands in the autotune table warm processes
        # consult
        "closure_kernel": _closure_kernel_probe(),
    }
    if dev.get("anomalies") != host.get("anomalies") \
            or dev.get("anomalies") != f32.get("anomalies") \
            or "G-single" not in (dev.get("anomalies") or ()):
        out["error"] = (f"classification drift: device "
                        f"{dev.get('anomalies')} vs f32 "
                        f"{f32.get('anomalies')} vs host "
                        f"{host.get('anomalies')}")
    elif lat.get("holds") != lat_host.get("holds"):
        out["error"] = (f"lattice drift: device holds "
                        f"{lat.get('holds')} vs host "
                        f"{lat_host.get('holds')}")
    return out


def _closure_kernel_probe(n: int = 1024, repeat: int = 3) -> dict:
    """Word-packed vs f32 closure bodies on a closure-BOUND graph
    (random cyclic, no trimmable fringe at this density): the kernel
    comparison the 100k rung's tiny trimmed core can't show. Records
    the winner in the autotune table (tools/closure_sweep.py is the
    full sweep; this keeps BENCH honest about the body in one run)."""
    import numpy as np

    from jepsen_tpu.checkers import autotune
    from jepsen_tpu.txn import cycles
    from jepsen_tpu.txn.infer import DepGraph

    r = np.random.default_rng(42)
    e = n * 2
    src = r.integers(0, n, e).astype(np.int32)
    dst = r.integers(0, n, e).astype(np.int32)
    keep = src != dst
    g = DepGraph(n=n, src=src[keep], dst=dst[keep],
                 et=r.integers(0, 3, int(keep.sum())).astype(np.int8),
                 txns=tuple(range(n)))

    def _t(no_word: bool) -> float:
        env = "JEPSEN_TPU_NO_WORD_CLOSURE"
        at = "JEPSEN_TPU_NO_AUTOTUNE"
        old = os.environ.pop(env, None)
        old_at = os.environ.pop(at, None)
        try:
            # a recorded winner must not steer the arm being measured
            os.environ[at] = "1"
            if no_word:
                os.environ[env] = "1"
            cycles.closure_booleans(g)          # warm
            best = float("inf")
            for _ in range(repeat):
                t0 = time.monotonic()
                cycles.closure_booleans(g)
                best = min(best, time.monotonic() - t0)
            return best
        finally:
            os.environ.pop(env, None)
            os.environ.pop(at, None)
            if old is not None:
                os.environ[env] = old
            if old_at is not None:
                os.environ[at] = old_at

    w, f = _t(False), _t(True)
    winner = "word" if w <= f else "f32"
    autotune.record("closure", autotune.closure_key(n), winner,
                    metric=1.0 / max(min(w, f), 1e-9))
    return {"Np": n, "word_s": round(w, 4), "f32_s": round(f, 4),
            "winner": winner,
            "speedup": round(f / max(w, 1e-9), 2)}


def walk_bodies_probe(model, packed, n_ops: int,
                      repeat: int = 2) -> dict:
    """The post-hoc kernel-body comparison on the headline history:
    ``reach.check_packed`` with the word-packed body FORCED vs the
    dense/pallas chain, verdicts asserted equal, winner recorded in
    the autotune table (``walk`` kind) that route selection consults
    on the next process. The 33x XLA:CPU step-cost folklore becomes a
    measured, persisted number."""
    from jepsen_tpu.checkers import autotune, events as ev, reach

    memo, stream, _T, S_pad, M = reach._prep(
        model, packed, max_states=100_000, max_slots=20,
        max_dense=1 << 22)
    W = max(stream.W, 1)
    rs = ev.returns_view(stream)

    def _t(body: str):
        env = ("JEPSEN_TPU_WORD_POSTHOC" if body == "word"
               else "JEPSEN_TPU_NO_WORD_WALK")
        old = os.environ.pop(env, None)
        os.environ[env] = "1"
        try:
            res = reach.check_packed(model, packed)     # warm
            best = float("inf")
            for _ in range(max(1, repeat)):
                t0 = time.monotonic()
                res = reach.check_packed(model, packed)
                best = min(best, time.monotonic() - t0)
            return res, best
        finally:
            os.environ.pop(env, None)
            if old is not None:
                os.environ[env] = old

    res_w, t_w = _t("word")
    res_d, t_d = _t("dense")
    out = {"geometry": {"S": memo.n_states, "W": W, "M": M,
                        "returns": int(rs.n_returns)},
           "word": {"check_s": round(t_w, 3),
                    "ops_s": round(n_ops / max(t_w, 1e-9)),
                    "engine": res_w.get("engine")},
           "dense": {"check_s": round(t_d, 3),
                     "ops_s": round(n_ops / max(t_d, 1e-9)),
                     "engine": res_d.get("engine")},
           "speedup_word_vs_dense": round(t_d / max(t_w, 1e-9), 2)}
    if res_w.get("valid") != res_d.get("valid"):
        out["error"] = (f"verdict drift: word {res_w.get('valid')} "
                        f"vs dense {res_d.get('valid')}")
        return out
    winner = "word" if t_w <= t_d else "dense"
    out["winner"] = winner
    out["recorded"] = autotune.record(
        "walk", autotune.walk_key(memo.n_states, W, M, rs.n_returns),
        winner, metric=n_ops / max(min(t_w, t_d), 1e-9))
    return out


def _ragged_lengths(total: int, keys: int = 12,
                    ratio: float = 1.45) -> list:
    """Deterministic mixed-length key split (BASELINE config #4 shape):
    a geometric spread over ``keys`` keys summing to ~``total`` ops, so
    lengths span several power-of-two buckets and the bucketed lane
    packer has real work to do."""
    w = [ratio ** -i for i in range(keys)]
    s = sum(w)
    return [max(24, int(total * x / s)) for x in w]


def independent_probe(model, n_ops: int, seed: int,
                      processes: int) -> dict:
    """Ragged independent-keys rung: ``n_ops`` total over >= 8 keys of
    mixed lengths through ``reach.check_many`` (the bucketed LOCKSTEP
    lane by default on TPU), against the sequential per-key
    ``check_packed`` baseline measured in the same run — the honest
    apples-to-apples the acceptance bar asks for. Reports per-bucket
    geometry, pack efficiency, kernel-cache counters, and aggregate
    ops/s for both paths."""
    from jepsen_tpu import fixtures
    from jepsen_tpu.checkers import reach

    lens = _ragged_lengths(n_ops)
    packeds = [fixtures.gen_packed("cas", n_ops=L, processes=processes,
                                   seed=seed + 500 + i)
               for i, L in enumerate(lens)]
    total = sum(lens)
    diag: dict = {}
    res = reach.check_many(model, packeds, diag=diag)   # warm/compile
    if not all(r["valid"] is True for r in res):
        return {"error": "bad ragged verdicts"}
    engines = sorted({r["engine"] for r in res})
    times = []
    best_diag = diag
    for _ in range(2):
        d: dict = {}
        t1 = time.monotonic()
        reach.check_many(model, packeds, diag=d)
        dt = time.monotonic() - t1
        if not times or dt < min(times):
            best_diag = d or diag
        times.append(dt)
    best = min(times)
    # sequential per-key baseline: same histories, same run, warmed
    # once, and timed with the SAME best-of-2 discipline as the batch
    # side so speedup_vs_sequential compares like with like
    for p in packeds:
        reach.check_packed(model, p)
    seq_times = []
    for _ in range(2):
        t1 = time.monotonic()
        for p in packeds:
            reach.check_packed(model, p)
        seq_times.append(time.monotonic() - t1)
    seq_s = max(min(seq_times), 1e-9)
    prep = best_diag.get("prep", {})
    mesh = best_diag.get("mesh") or {}
    return {"keys": len(lens), "lens": lens,
            "e2e_s": round(best, 3),
            "agg_ops_s": round(total / best),
            "seq_s": round(seq_s, 3),
            "seq_ops_s": round(total / seq_s),
            "speedup_vs_sequential": round(seq_s / best, 2),
            "engine": engines,
            "n_devices": mesh.get("n_devices", 1),
            "per_device_groups": mesh.get("per_device_groups"),
            "mesh_pad_lanes": mesh.get("pad_lanes", 0),
            "prep_s": prep.get("wall_s"),
            "prep_hidden_s": prep.get("hidden_s"),
            "prep_mode": prep.get("mode"),
            "dispatch_s": best_diag.get("dispatch_s"),
            "fetch_s": best_diag.get("fetch_s"),
            "transfer": best_diag.get("transfer"),
            "pack_efficiency": best_diag.get("pack_efficiency"),
            "real_returns": best_diag.get("real_returns"),
            "padded_returns": best_diag.get("padded_returns"),
            "kernel_cache": best_diag.get("kernel_cache"),
            "per_bucket": best_diag.get("groups", [])}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=100_000)
    ap.add_argument("--processes", type=int, default=5)
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--engine", default="reach",
                    choices=["reach", "chunked", "batch", "wgl-cpu",
                             "wgl-native"])
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--no-batch", action="store_true",
                    help="skip the lockstep batch probe")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="write a jax.profiler trace of one steady-state "
                         "check to DIR")
    ap.add_argument("--trace", metavar="PATH", default="trace.json",
                    help="write the obs span trace (Chrome trace_event "
                         "JSON; '' disables)")
    ap.add_argument("--quick", action="store_true",
                    help="small/CI run: caps --ops at 20k, one repeat, "
                         "skips the batch probe — the transfer-guard "
                         "CI step's configuration")
    ap.add_argument("--serve", action="store_true",
                    help="append the 'serve' sub-object: an "
                         "in-process check daemon driven by the "
                         "open-loop load generator (req/s, p50/p99 "
                         "verdict latency)")
    ap.add_argument("--txn", action="store_true",
                    help="append the 'txn' sub-object: the "
                         "transactional rung — a --ops-txn "
                         "list-append history with an injected "
                         "anomaly, MXU closure vs host SCC "
                         "(agg txns/s both ways)")
    args = ap.parse_args()
    if args.quick:
        args.ops = min(args.ops, 20_000)
        args.repeat = 1
        args.no_batch = True

    from jepsen_tpu import fixtures, models, obs, store
    from jepsen_tpu.checkers import reach, wgl_ref

    # persistent compilation cache (ISSUE 3): a cold second process
    # re-running bench.py loads every kernel geometry from disk instead
    # of recompiling — first-iteration latency drops and
    # compile_cache.hits > 0 lands in the output. JEPSEN_TPU_NO_PERSIST=1
    # reverts to cacheless runs.
    cc_dir = store.enable_compilation_cache()

    def _finish(out: dict, probe_engine) -> None:
        # the bench selects its engine explicitly — record it in the
        # ledger so the obs sub-object names what was measured, then
        # attach the counters/ledger snapshot and write the trace
        obs.decision(str(probe_engine or args.engine), "selected",
                     cause="bench-cli", ops=args.ops)
        snap = obs.snapshot()
        out["obs"] = snap
        counters = snap.get("counters", {})
        out["compile_cache"] = {
            "dir": cc_dir,
            "hits": int(counters.get("compile_cache.hits", 0)),
            "requests": int(counters.get("compile_cache.requests", 0)),
        }
        if args.trace:
            try:
                out["trace_file"] = obs.export_trace(args.trace)
            except OSError as e:
                out["trace_file"] = f"error: {e}"

    if args.engine == "batch":
        # the batch dimension AS the headline: ragged independent-keys
        # through the bucketed lockstep lane, vs the sequential
        # per-key baseline in the same run
        model = models.cas_register()
        with obs.span("bench.independent_probe", ops=args.ops):
            probe = independent_probe(model, args.ops, args.seed,
                                      args.processes)
        agg = probe.get("agg_ops_s", 0) or 0
        baseline_floor = 100_000 / 60.0
        out = {"metric": (f"independent-{args.ops // 1000}k-cas-"
                          f"x{probe.get('keys', 0)}"),
               "value": float(agg), "unit": "ops/s",
               "vs_baseline": round(agg / baseline_floor, 2),
               "batch": probe}
        _finish(out, (probe.get("engine") or ["reach-many"])[0])
        print(json.dumps(out))
        return 0 if "error" not in probe else 1

    t0 = time.monotonic()
    # native packed-level generation: at 10M ops the Python tick loop
    # plus Op/Entry materialization took ~224 s — the C++ simulation
    # emits the packed arrays directly in <1 s (same construction, so
    # still linearizable by definition)
    packed = fixtures.gen_packed("cas", n_ops=args.ops,
                                 processes=args.processes, seed=args.seed)
    gen_s = time.monotonic() - t0
    model = models.cas_register()

    def run():
        if args.engine == "reach":
            return reach.check_packed(model, packed)
        if args.engine == "chunked":
            return reach.check_chunked(model, packed=packed)
        if args.engine == "wgl-native":
            from jepsen_tpu.checkers import wgl_native
            return wgl_native.check_packed(model, packed)
        return wgl_ref.check_packed(model, packed, time_limit=300)

    # warm-up: first call pays jit compilation (or a persistent-cache
    # load on a warm start — first_iter_s in the output is the number
    # that drops when compile_cache.hits > 0); the measurement is
    # steady state (compile caches persist across runs of the same
    # shapes).
    t1 = time.monotonic()
    with obs.span("bench.warm", engine=args.engine, ops=args.ops):
        res = run()
    first_iter_s = time.monotonic() - t1
    if res["valid"] is not True:
        # the ledger explaining WHICH engine produced the bad verdict
        # (and what fell back en route) ships with the error too
        out = {"metric": "linearize-100k-cas",
               "value": 0.0, "unit": "ops/s",
               "vs_baseline": 0.0,
               "error": f"bad verdict {res.get('valid')}"}
        _finish(out, res.get("engine"))
        print(json.dumps(out))
        return 1
    times = []
    if args.profile:
        # SURVEY.md §5 tracing: a jax.profiler trace of the steady-state
        # solver, viewable in TensorBoard / Perfetto
        import jax
        with jax.profiler.trace(args.profile):
            t1 = time.monotonic()
            res = run()
            times.append(time.monotonic() - t1)
    for i in range(max(1, args.repeat)):
        t1 = time.monotonic()
        with obs.span("bench.measure", engine=args.engine, rep=i):
            res = run()
        times.append(time.monotonic() - t1)
    best = min(times)
    ops_per_s = args.ops / best
    baseline_floor = 100_000 / 60.0
    out = {
        "metric": f"linearize-{args.ops // 1000}k-cas",
        "value": round(ops_per_s, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_s / baseline_floor, 2),
        "check_s": round(best, 3),
        "first_iter_s": round(first_iter_s, 3),
        "gen_s": round(gen_s, 2),
        "engine": res.get("engine"),
        "valid": res.get("valid"),
        "events": res.get("events"),
        "slots": res.get("slots"),
    }
    if args.engine == "reach":
        # both probes measure the same lane operand set: prep it once
        probe_prep, probe_prep_s = None, None
        try:
            t_pp = time.monotonic()
            probe_prep = _lane_operands(model, packed)
            probe_prep_s = time.monotonic() - t_pp
        except Exception:                               # noqa: BLE001
            pass        # each probe reports its own failure below
        try:
            # host-only marshalling breakdown — works on CPU-only CI,
            # where the device probes below skip; the transfer-guard
            # step budgets these numbers
            out["transfer"] = transfer_probe(model, packed,
                                             prep=probe_prep)
        except Exception as e:                          # noqa: BLE001
            out["transfer"] = {"error": f"{type(e).__name__}: {e}"}
        # the two Pallas probes measure compiled-kernel timings: on the
        # CPU backend Pallas only runs in interpret mode, whose
        # timings would be misleading — a structured skip, never a raw
        # exception string in the bench JSON (BENCH r08 regression)
        pallas_cpu = _pallas_needs_accelerator()
        if pallas_cpu:
            out["kernel"] = {"skipped": "pallas-needs-accelerator"}
        else:
            try:
                out["kernel"] = kernel_probe(model, packed,
                                             prep=probe_prep,
                                             prep_s=probe_prep_s)
            except Exception as e:                      # noqa: BLE001
                # probe is diagnostics, not the metric: histories the
                # lane kernel does not admit skip it
                out["kernel"] = {"error": f"{type(e).__name__}: {e}"}
        if pallas_cpu:
            out["chunklock"] = {"skipped": "pallas-needs-accelerator"}
        else:
            try:
                out["chunklock"] = chunklock_probe(model, packed)
            except Exception as e:                      # noqa: BLE001
                out["chunklock"] = {"error":
                                    f"{type(e).__name__}: {e}"}
        try:
            # post-hoc kernel BODIES on this rung's history: the
            # word-packed walk vs the dense/pallas chain, winner
            # persisted in the autotune table (warm processes then
            # route check_packed through the recorded winner)
            out["walk_bodies"] = walk_bodies_probe(model, packed,
                                                   args.ops)
        except Exception as e:                          # noqa: BLE001
            out["walk_bodies"] = {"error":
                                  f"{type(e).__name__}: {e}"}
        if not args.no_batch and args.ops <= 200_000:
            try:
                out["batch"] = batch_probe(model, args.ops, args.seed,
                                           args.processes)
            except Exception as e:                      # noqa: BLE001
                out["batch"] = {"error": f"{type(e).__name__}: {e}"}
    if args.serve:
        try:
            with obs.span("bench.serve_probe"):
                out["serve"] = serve_probe(quick=args.quick)
        except Exception as e:                          # noqa: BLE001
            out["serve"] = {"error": f"{type(e).__name__}: {e}"}
        try:
            # the streaming-session rung rides --serve: sustained
            # appends/s + p99 append-to-verdict vs the host online
            # monitor on the same op stream
            with obs.span("bench.session_probe"):
                out["session"] = session_probe(
                    n_ops=min(args.ops, 100_000), seed=args.seed,
                    quick=args.quick)
        except Exception as e:                          # noqa: BLE001
            out["session"] = {"error": f"{type(e).__name__}: {e}"}
        try:
            # the multiplexing rung (ISSUE 16): L live streams, one
            # vmapped launch per wave vs L per-session launches
            with obs.span("bench.session_mux_probe"):
                out["session_mux"] = session_mux_probe(
                    quick=args.quick)
        except Exception as e:                          # noqa: BLE001
            out["session_mux"] = {"error":
                                  f"{type(e).__name__}: {e}"}
    if args.txn:
        try:
            with obs.span("bench.txn_probe", txns=args.ops):
                out["txn"] = txn_probe(args.ops, args.seed)
        except Exception as e:                          # noqa: BLE001
            out["txn"] = {"error": f"{type(e).__name__}: {e}"}
    _finish(out, res.get("engine"))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
