"""Closure-body sweep: measure the word-packed vs f32 txn closure
across padded-geometry rungs and PERSIST the winners in the autotune
table (``<store-root>/.cache/autotune.json``), so ``txn/cycles.py``
route selection consults a measured record instead of re-deriving
folklore per process.

Each rung builds a random cyclic dependency graph at the target
padded size, times both one-shot bodies warm (best of ``--repeat``),
asserts their 4 booleans equal each other AND the host Tarjan/SCC
reference (a sweep must never record a winner that changes
verdicts), and records the winner under ``closure|<backend>|Np<n>``.

Usage: python tools/closure_sweep.py [--rungs 64,256,1024]
       [--repeat 3] [--no-record]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def rand_graph(n: int, e: int, seed: int):
    from jepsen_tpu.txn.infer import DepGraph
    r = np.random.default_rng(seed)
    src = r.integers(0, n, e)
    dst = r.integers(0, n, e)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    et = r.integers(0, 3, len(src)).astype(np.int8)
    return DepGraph(n=n, src=src.astype(np.int32),
                    dst=dst.astype(np.int32), et=et,
                    txns=tuple(range(n)))


def _time_body(graph, body: str, repeat: int) -> float:
    from jepsen_tpu.txn import cycles
    env = "JEPSEN_TPU_NO_WORD_CLOSURE"
    at = "JEPSEN_TPU_NO_AUTOTUNE"
    old = os.environ.pop(env, None)
    old_at = os.environ.pop(at, None)
    try:
        # a previously-recorded winner must not steer the arm being
        # measured (with the table live, a recorded "f32" makes the
        # "word" arm silently time f32 against itself)
        os.environ[at] = "1"
        if body == "f32":
            os.environ[env] = "1"
        cycles.closure_booleans(graph)              # warm/compile
        best = float("inf")
        for _ in range(max(1, repeat)):
            t0 = time.monotonic()
            cycles.closure_booleans(graph)
            best = min(best, time.monotonic() - t0)
        return best
    finally:
        os.environ.pop(env, None)
        os.environ.pop(at, None)
        if old is not None:
            os.environ[env] = old
        if old_at is not None:
            os.environ[at] = old_at


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rungs", default="64,256,1024",
                    help="comma-separated graph sizes (each pads to "
                         "its power-of-two closure geometry)")
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--edges-per-node", type=float, default=2.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--no-record", action="store_true",
                    help="measure + differential only; do not write "
                         "the autotune table")
    args = ap.parse_args()

    from jepsen_tpu.checkers import autotune
    from jepsen_tpu.txn import cycles, host_ref

    rc = 0
    for n in (int(x) for x in args.rungs.split(",")):
        g = rand_graph(n, max(1, int(n * args.edges_per_node)),
                       args.seed)
        # verdict identity first: a body sweep that records a winner
        # with different booleans would be poisoning route selection
        ref = host_ref.classify_booleans(g)
        os.environ["JEPSEN_TPU_NO_AUTOTUNE"] = "1"
        try:
            word_b = cycles.closure_booleans(g)
            os.environ["JEPSEN_TPU_NO_WORD_CLOSURE"] = "1"
            try:
                f32_b = cycles.closure_booleans(g)
            finally:
                os.environ.pop("JEPSEN_TPU_NO_WORD_CLOSURE", None)
        finally:
            os.environ.pop("JEPSEN_TPU_NO_AUTOTUNE", None)
        if not (word_b == f32_b == ref):
            print(json.dumps({"rung": n, "error": "verdict mismatch",
                              "word": word_b, "f32": f32_b,
                              "host": ref}), flush=True)
            rc = 1
            continue
        t_word = _time_body(g, "word", args.repeat)
        t_f32 = _time_body(g, "f32", args.repeat)
        winner = "word" if t_word <= t_f32 else "f32"
        row = {"rung": n, "Np": cycles._pad_n(g.n),
               "edges": int(g.e),
               "word_s": round(t_word, 5), "f32_s": round(t_f32, 5),
               "winner": winner,
               "speedup": round(t_f32 / max(t_word, 1e-9), 2)}
        if not args.no_record:
            path = autotune.record(
                "closure", autotune.closure_key(g.n), winner,
                metric=1.0 / max(min(t_word, t_f32), 1e-9),
                detail={"word_s": row["word_s"],
                        "f32_s": row["f32_s"]})
            row["recorded"] = path
        print(json.dumps(row), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
