"""CI transfer-guard: fail the build when the wire format regresses.

The round-6 transfer diet (narrow dtypes + bit-packed bools, see
``jepsen_tpu/checkers/transfer.py`` and ENGINE.md §"The transfer
diet") is easy to lose silently — one re-widened ``.astype(np.int32)``
or an unpacked bool tensor restores the blanket format and nothing
crashes, the link just carries 4-8x the bytes again. This guard pins
the diet with a checked-in budget (``data/transfer_budget.json``):

- runs ``bench.py --quick`` (deterministic seeded history; the
  ``transfer`` sub-object is the HOST-ONLY marshalling breakdown of
  the production operand packing, so the guard works on CPU-only CI
  without a device dispatch), or reads a pre-captured bench JSON via
  ``--bench-json``;
- fails (exit 1) when ``packed_bytes`` exceeds ``max_packed_bytes``
  or the unpacked/packed ``ratio`` drops below ``min_ratio``;
- exits 3 when the probe itself is missing/broken — a guard that
  cannot measure must not pass.

Usage:
    python tools/transfer_guard.py [--budget data/transfer_budget.json]
                                   [--bench-json PATH] [--ops 20000]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Any, Dict

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)


def run_quick_bench(ops: int) -> Dict[str, Any]:
    """Run ``bench.py --quick`` in a subprocess (its own backend init)
    and parse the final JSON line — bench prints progress lines first,
    the result object last."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, os.path.join(_REPO, "bench.py"),
           "--quick", "--ops", str(ops), "--trace", ""]
    p = subprocess.run(cmd, cwd=_REPO, env=env, text=True,
                       stdout=subprocess.PIPE)
    if p.returncode != 0:
        raise RuntimeError(f"bench.py --quick exited {p.returncode}")
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise RuntimeError("no JSON object in bench.py output")


def check(bench: Dict[str, Any], budget: Dict[str, Any]) -> Dict[str, Any]:
    """Compare the bench ``transfer`` sub-object against the budget.
    Returns a verdict dict with ``ok`` plus per-check detail."""
    xfer = bench.get("transfer")
    if (not isinstance(xfer, dict) or "error" in xfer
            or "packed_bytes" not in xfer or "ratio" not in xfer):
        return {"ok": False, "probe_missing": True,
                "detail": xfer if xfer else "no 'transfer' sub-object"}
    packed = int(xfer["packed_bytes"])
    ratio = float(xfer["ratio"])
    max_packed = int(budget["max_packed_bytes"])
    min_ratio = float(budget["min_ratio"])
    checks = {
        "packed_bytes": {"measured": packed, "max": max_packed,
                         "ok": packed <= max_packed},
        "ratio": {"measured": ratio, "min": min_ratio,
                  "ok": ratio >= min_ratio},
    }
    # gates must be at their shipping defaults when the budget is
    # enforced — a CI env var that opts the diet out would let a real
    # regression hide behind an artificially-exempt measurement
    gates = xfer.get("gates", {})
    checks["gates_default"] = {"measured": gates,
                              "ok": all(gates.values()) if gates
                              else False}
    return {"ok": all(c["ok"] for c in checks.values()),
            "checks": checks,
            "fetch_mode": xfer.get("fetch_mode"),
            "bytes_per_return": xfer.get("bytes_per_return")}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget",
                    default=os.path.join(_REPO, "data",
                                         "transfer_budget.json"))
    ap.add_argument("--bench-json", default=None,
                    help="pre-captured bench output (skips running "
                         "bench.py --quick)")
    ap.add_argument("--ops", type=int, default=20_000,
                    help="history size for the quick bench run")
    args = ap.parse_args()

    with open(args.budget) as f:
        budget = json.load(f)
    try:
        if args.bench_json:
            with open(args.bench_json) as f:
                bench = json.load(f)
        else:
            bench = run_quick_bench(args.ops)
    except (OSError, RuntimeError, json.JSONDecodeError) as e:
        print(json.dumps({"ok": False, "probe_missing": True,
                          "detail": f"{type(e).__name__}: {e}"}))
        return 3

    verdict = check(bench, budget)
    print(json.dumps(verdict, indent=2))
    if verdict.get("probe_missing"):
        return 3
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
