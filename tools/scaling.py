"""Multi-device scaling curve on the virtual CPU mesh.

Measures the four sharded checker paths at 1/2/4/8 devices
(`--devices` to override), one subprocess per device count (the XLA
device count is fixed at backend init):

- **keyed**  — `check_many` with the key axis sharded over the mesh
  (the `independent` hot path; data-parallel axis);
- **chunked** — `check_chunked` boolean transfer matrices with the
  chunk axis sharded via `shard_map` (history/sequence-parallel axis);
- **frontier** — the sparse engine with config rows hash-routed to
  owner shards via `all_to_all`;
- **lockstep** — `check_batch(devices=...)` through the mesh-lockstep
  lane (lockstep lane blocks placed per device, dispatch groups
  multi-queued). The CPU sweep has no Pallas hardware, so the lockstep
  gates are forced open with the kernel in interpret mode — the row
  measures the multi-queue scheduler and verdict fidelity under
  sharding, not kernel speed.

IMPORTANT caveat, printed with the results: on a host with fewer
physical cores than virtual devices the curve measures *sharding
overhead*, not parallel speedup — XLA's virtual CPU devices share the
host's cores. A flat curve on a 1-core host is the success criterion
there (the sharded program does ~1x total work); real speedup needs
real chips (or >= n_devices cores). `__graft_entry__.dryrun_multichip`
asserts a conservative >= 2x keyed speedup floor at 8 devices when the
host has the cores to show it (the timed region includes serial host
prep and per-iteration liveness all-reduces).

Usage: python tools/scaling.py [--devices 1,2,4,8] [--keys 512]
       [--chunk-ops 100000] [--quick]
Emits one JSON line per (path, n_devices) plus a final summary line
collecting best_s per path across the device counts.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)


def _worker(n_dev: int, keys: int, key_ops: int, chunk_ops: int,
            n_chunks: int, lockstep_keys: int,
            lockstep_ops: int) -> int:
    """Runs inside the subprocess: measure all four paths on an
    ``n_dev``-device mesh and print one JSON line per path."""
    import jax

    from jepsen_tpu import fixtures, models
    from jepsen_tpu.checkers import frontier, reach
    from jepsen_tpu.history import pack

    devs = jax.devices()[:n_dev]
    model = models.cas_register()

    def best_of(fn, n=2):
        fn()                                     # warm / compile
        best = float("inf")
        for _ in range(n):
            t0 = time.monotonic()
            fn()
            best = min(best, time.monotonic() - t0)
        return best

    # keyed: N keys sharded over the mesh
    packs = [pack(fixtures.gen_history("cas", n_ops=key_ops, processes=3,
                                       seed=s))
             for s in range(keys)]
    dt = best_of(lambda: reach.check_many(model, packs, devices=devs))
    print(json.dumps({"path": "keyed", "n_devices": n_dev, "keys": keys,
                      "key_ops": key_ops, "best_s": round(dt, 3)}),
          flush=True)

    # chunked: one long history, chunk axis sharded
    hist = fixtures.gen_history("cas", n_ops=chunk_ops, processes=5,
                                seed=42)
    packed = pack(hist)
    dt = best_of(lambda: reach.check_chunked(
        model, packed=packed, n_chunks=n_chunks, devices=devs,
        max_matrix=1 << 28))
    print(json.dumps({"path": "chunked", "n_devices": n_dev,
                      "ops": chunk_ops, "n_chunks": n_chunks,
                      "best_s": round(dt, 3)}), flush=True)

    # frontier: crash-seasoned register history, rows hash-routed.
    # crash parameters are deliberately light: every crashed op stays
    # forever-pending, and distinct-value crashed writes multiply the
    # quotiented config space (2 values / 1% keeps the set ~8k rows)
    hist = fixtures.gen_history("register", n_ops=1200, processes=4,
                                crash_p=0.01, values=2, seed=11)
    dt = best_of(lambda: frontier.check(models.register(), hist,
                                        frontier0=512, devices=devs))
    print(json.dumps({"path": "frontier", "n_devices": n_dev,
                      "ops": 1200, "best_s": round(dt, 3)}), flush=True)

    # lockstep: H complete histories through check_batch(devices=...) →
    # the mesh-lockstep lane. No Pallas hardware on the CPU sweep, so
    # the gates are forced open with the kernel in interpret mode
    # (LAST path in this worker — the patched gates must not leak into
    # the measurements above); an injected violation proves verdict
    # fidelity under sharding on every rung, and the ENGINE is asserted
    # so a silent decline to the keyed mesh-union walk can never be
    # reported as lockstep scaling data.
    from jepsen_tpu.checkers import preproc_native, reach_batch
    if not preproc_native.available():
        print(json.dumps({"path": "lockstep", "n_devices": n_dev,
                          "skipped": "native preprocessing library "
                                     "unavailable"}), flush=True)
        return 0
    reach._use_pallas = lambda: True
    reach._PALLAS_MIN_RETURNS = 0
    reach_batch._INTERPRET_DEFAULT = True
    for k in ("JEPSEN_TPU_NO_MESH_LOCKSTEP", "JEPSEN_TPU_NO_STREAM_PREP",
              "JEPSEN_TPU_NO_PACKED_XFER", "JEPSEN_TPU_NO_LAZY_FETCH",
              "JEPSEN_TPU_NO_DONATE"):
        os.environ.pop(k, None)   # the rung measures the mesh lane on
    #                               the full transfer diet (ISSUE 5)
    from jepsen_tpu.checkers import transfer
    # covers all three gates, and catches an env-var rename drifting
    # from the pop list above (which would silently re-close a gate)
    assert (transfer.packed_enabled() and transfer.lazy_fetch_enabled()
            and transfer.donate_enabled()), "diet gates must be open"
    packs_l = []
    for s in range(lockstep_keys):
        h = fixtures.gen_history("cas", n_ops=lockstep_ops, processes=3,
                                 seed=300 + s)
        if s == 1:
            h = fixtures.corrupt(h, seed=s)
        packs_l.append(pack(h))
    want = "reach-lockstep-mesh" if n_dev > 1 else "reach-lockstep"

    def _lockstep():
        res = reach.check_batch(model, packs_l, devices=devs)
        assert all(r["engine"] == want for r in res), \
            sorted({r["engine"] for r in res})
        assert res[1]["valid"] is False and all(
            r["valid"] is True for i, r in enumerate(res) if i != 1), \
            "lockstep verdicts drifted under sharding"
        # the lazy-fetch rescue (ISSUE 5): with verdicts fetched as
        # per-lane summaries, the full arrays cross the wire only when
        # a lane dies and witness reconstruction needs them — assert
        # per rung that the injected violation still surfaces its
        # knossos-style witness, so the rescue path is covered at
        # every mesh width
        assert res[1].get("final-configs"), \
            "lazy-fetch rescue lost the violation witness"
        assert res[1].get("op") is not None, \
            "lazy-fetch rescue lost the failing op"
        return res

    dt = best_of(_lockstep)
    print(json.dumps({"path": "lockstep", "n_devices": n_dev,
                      "engine": want, "keys": lockstep_keys,
                      "key_ops": lockstep_ops,
                      "best_s": round(dt, 3)}), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4,8")
    ap.add_argument("--keys", type=int, default=512)
    ap.add_argument("--key-ops", type=int, default=100)
    ap.add_argument("--chunk-ops", type=int, default=100_000)
    ap.add_argument("--n-chunks", type=int, default=64)
    ap.add_argument("--lockstep-keys", type=int, default=16)
    ap.add_argument("--lockstep-ops", type=int, default=600)
    ap.add_argument("--quick", action="store_true",
                    help="small sizes for CI")
    ap.add_argument("--_worker", type=int, default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.quick:
        args.keys, args.chunk_ops, args.n_chunks = 64, 10_000, 16
        args.lockstep_keys, args.lockstep_ops = 8, 240

    if args._worker is not None:
        return _worker(args._worker, args.keys, args.key_ops,
                       args.chunk_ops, args.n_chunks,
                       args.lockstep_keys, args.lockstep_ops)

    counts = [int(x) for x in args.devices.split(",")]
    cores = os.cpu_count() or 1
    print(json.dumps({"host_cores": cores, "note":
                      "with host_cores < n_devices the curve measures "
                      "sharding overhead, not speedup"}), flush=True)
    rows = []
    for n in counts:
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count={n}"
                            ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, os.path.abspath(__file__),
               "--_worker", str(n),
               "--keys", str(args.keys), "--key-ops", str(args.key_ops),
               "--chunk-ops", str(args.chunk_ops),
               "--n-chunks", str(args.n_chunks),
               "--lockstep-keys", str(args.lockstep_keys),
               "--lockstep-ops", str(args.lockstep_ops)]
        # stdout is relayed line-by-line (the multi-minute sweep stays
        # live) while the rows are collected for the summary; stderr
        # passes through untouched so worker warnings are never lost
        p = subprocess.Popen(cmd, env=env, cwd=_REPO,
                             stdout=subprocess.PIPE, text=True)
        assert p.stdout is not None
        for line in p.stdout:
            sys.stdout.write(line)
            sys.stdout.flush()
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "path" in d and "best_s" in d:
                rows.append(d)
        rc = p.wait()
        if rc != 0:
            print(json.dumps({"n_devices": n, "error": rc}),
                  flush=True)
    # summary: best_s per path across the device sweep (the
    # flat-curve-on-few-cores caveat from the header line applies)
    summary: dict = {}
    for d in rows:
        summary.setdefault(d["path"], {})[str(d["n_devices"])] = \
            d["best_s"]
    print(json.dumps({"summary": summary, "host_cores": cores}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
