"""On-chip ablation harness for the single-history lane kernel.

Builds the cas-100k operand set once, then times kernel VARIANTS by
dispatch slope (K queued dispatches + 1 fetch, minus 1 dispatch +
fetch — ``block_until_ready`` is a no-op over the dev tunnel). Used to
drive the round-3 kernel redesign; results land in BASELINE.md.

Usage: python tools/ablate_lane.py [--ops N] [--variants a,b,...]
"""
from __future__ import annotations

import argparse
import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_BLOCK = 1024


def _probe(run, args, K: int = 6):
    import numpy as np
    _ = np.asarray(run(*args)[1])               # warm/compile
    t0 = time.monotonic()
    _ = np.asarray(run(*args)[1])
    one_s = time.monotonic() - t0
    t0 = time.monotonic()
    outs = [run(*args) for _ in range(K)]
    _ = np.asarray(outs[-1][1])
    many_s = time.monotonic() - t0
    return max(0.0, (many_s - one_s) / (K - 1))


# -- pass bodies -------------------------------------------------------------

def _fire_bool(R, G_all, W, M, S):
    """Round-2 pass: boolean compare+cast, serial max merge."""
    import jax.numpy as jnp
    F = jnp.dot(R, G_all, preferred_element_type=jnp.float32)
    for jj in range(W):
        Fj = F[:, jj * S:(jj + 1) * S]
        half, blk = M >> (jj + 1), 1 << jj
        Rr = R.reshape(half, 2, blk, S)
        Fr = Fj.reshape(half, 2, blk, S)
        hi = jnp.maximum(Rr[:, 1], (Fr[:, 0] > 0.5).astype(jnp.float32))
        R = jnp.stack([Rr[:, 0], hi], axis=1).reshape(M, S)
    return R


def _fire_counts_tree(R, G_all, W, M, S):
    """Counts, balanced add tree."""
    import jax.numpy as jnp
    F = jnp.dot(R, G_all, preferred_element_type=jnp.float32)
    vals = [R]
    for jj in range(W):
        Fj = F[:, jj * S:(jj + 1) * S]
        half, blk = M >> (jj + 1), 1 << jj
        lo = Fj.reshape(half, 2, blk, S)[:, 0]
        vals.append(jnp.stack([jnp.zeros_like(lo), lo],
                              axis=1).reshape(M, S))
    while len(vals) > 1:
        nxt = [vals[i] + vals[i + 1] for i in range(0, len(vals) - 1, 2)]
        if len(vals) % 2:
            nxt.append(vals[-1])
        vals = nxt
    return vals[0]


def _fire_counts_gs(R, G_all, W, M, S):
    """Counts, Gauss-Seidel-shaped serial merge (add replaces max,
    compare+cast dropped — minimal diff from round 2)."""
    import jax.numpy as jnp
    F = jnp.dot(R, G_all, preferred_element_type=jnp.float32)
    for jj in range(W):
        Fj = F[:, jj * S:(jj + 1) * S]
        half, blk = M >> (jj + 1), 1 << jj
        Rr = R.reshape(half, 2, blk, S)
        Fr = Fj.reshape(half, 2, blk, S)
        hi = Rr[:, 1] + Fr[:, 0]
        R = jnp.stack([Rr[:, 0], hi], axis=1).reshape(M, S)
    return R


def _fire_bool_rev(R, G_all, W, M, S):
    """Round-2 pass with the Gauss-Seidel slot sweep REVERSED: chains
    that linearize in descending slot order complete in one pass."""
    import jax.numpy as jnp
    F = jnp.dot(R, G_all, preferred_element_type=jnp.float32)
    for jj in reversed(range(W)):
        Fj = F[:, jj * S:(jj + 1) * S]
        half, blk = M >> (jj + 1), 1 << jj
        Rr = R.reshape(half, 2, blk, S)
        Fr = Fj.reshape(half, 2, blk, S)
        hi = jnp.maximum(Rr[:, 1], (Fr[:, 0] > 0.5).astype(jnp.float32))
        R = jnp.stack([Rr[:, 0], hi], axis=1).reshape(M, S)
    return R


def _fire_maxnc(R, G_all, W, M, S):
    """Round-2 structure with the compare+cast dropped: max against the
    raw f32 contraction (values grow ≤8x per pass; one min(R,1) clamp
    per return restores the 0/1 scale — zero/nonzero is preserved)."""
    import jax.numpy as jnp
    F = jnp.dot(R, G_all, preferred_element_type=jnp.float32)
    for jj in range(W):
        Fj = F[:, jj * S:(jj + 1) * S]
        half, blk = M >> (jj + 1), 1 << jj
        Rr = R.reshape(half, 2, blk, S)
        Fr = Fj.reshape(half, 2, blk, S)
        hi = jnp.maximum(Rr[:, 1], Fr[:, 0])
        R = jnp.stack([Rr[:, 0], hi], axis=1).reshape(M, S)
    return R


# -- projection bodies -------------------------------------------------------

def _proj_blend(R, j, W, M, S, counts: bool):
    import jax.numpy as jnp
    acc = R * (j < 0).astype(jnp.float32)
    for jj in range(W):
        half, blk = M >> (jj + 1), 1 << jj
        Rr = R.reshape(half, 2, blk, S)
        taken = Rr[:, 1]
        p = jnp.stack([taken, jnp.zeros_like(taken)],
                      axis=1).reshape(M, S)
        acc = acc + p * (j == jj).astype(jnp.float32)
    return jnp.minimum(acc, 1.0) if counts else acc


def _proj_table_np(W, M):
    PJ = np.zeros((W + 1, M, M), np.float32)
    m = np.arange(M)
    for j in range(W):
        clear = (m & (1 << j)) == 0
        PJ[j, m[clear], (m | (1 << j))[clear]] = 1.0
    PJ[W] = np.eye(M, dtype=np.float32)
    return PJ


# -- kernel factory ----------------------------------------------------------

def make_call(B, W, M, S, O1, R_pad, n_pass, fire, proj_kind,
              counts, unroll=1, cgate=0):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jepsen_tpu.checkers.reach_pallas import _gather_G

    n_blocks = R_pad // B
    use_pj = proj_kind == "matmul"

    def kernel(ret_slot_ref, slot_ops_ref, extra_ref, P_ref, PJ_ref,
               R0_ref, ckpt_ref, final_ref, R_scr, G_scr, PJ_scr):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            R_scr[:] = R0_ref[:]

        ckpt_ref[0] = R_scr[:]
        G_scr[0] = _gather_G(slot_ops_ref, P_ref, 0, W, O1)
        if use_pj:
            j0 = ret_slot_ref[0]
            PJ_scr[0] = PJ_ref[jnp.where(j0 < 0, W, j0)]

        def one(k, R):
            G_all = G_scr[k % 2]
            if use_pj:
                PJk = PJ_scr[k % 2]
            kn = jnp.minimum(k + 1, B - 1)
            G_scr[(k + 1) % 2] = _gather_G(slot_ops_ref, P_ref, kn, W, O1)
            if use_pj:
                jn = ret_slot_ref[kn]
                PJ_scr[(k + 1) % 2] = PJ_ref[jnp.where(jn < 0, W, jn)]
            fires = fire if isinstance(fire, tuple) else (fire,)
            for _p in range(n_pass):
                R = fires[_p % len(fires)](R, G_all, W, M, S)
            if cgate:
                # deep-chain returns (pending count c > threshold) run
                # their remaining exact passes under untaken-free
                # pl.whens: R_scr carries the result across gates
                R_scr[:] = R
                off = n_pass
                for g in cgate:
                    def _deep(off=off, g=g):
                        Rd = R_scr[:]
                        for _p in range(g):
                            Rd = fires[(off + _p) % len(fires)](
                                Rd, G_all, W, M, S)
                        R_scr[:] = Rd
                    pl.when(extra_ref[k] > off)(_deep)
                    off += g
                R = R_scr[:]
            if use_pj:
                R = jnp.dot(PJk, R, preferred_element_type=jnp.float32)
                if counts:
                    R = jnp.minimum(R, 1.0)
            else:
                R = _proj_blend(R, ret_slot_ref[k], W, M, S, counts)
            return R

        def do_return(k, _):
            R = R_scr[:]
            for u in range(unroll):
                R = one(k * unroll + u, R)
            R_scr[:] = R
            return 0

        jax.lax.fori_loop(0, B // unroll, do_return, 0)

        @pl.when(step == n_blocks - 1)
        def _finish():
            final_ref[:] = R_scr[:]

    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((B,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((B * W,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((B,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((O1, S, S), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((W + 1, M, M), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((M, S), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, M, S), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((M, S), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_blocks, M, S), jnp.float32),
            jax.ShapeDtypeStruct((M, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((M, S), jnp.float32),
            pltpu.VMEM((2, S, W * S), jnp.float32),
            pltpu.VMEM((2, M, M), jnp.float32),
        ],
    )

    def run(ret_slot, slot_ops, P, PJ, R0):
        so = slot_ops.astype(jnp.int32)
        extra = (so.reshape(R_pad, W) >= 0).sum(axis=1)
        return call(ret_slot.astype(jnp.int32), so,
                    extra.astype(jnp.int32), P, PJ, R0)

    return jax.jit(run)


def make_call_stream(B, W, M, S, O1, R_pad, n_pass, fire, counts,
                     g_dtype="float32"):
    """Streamed-G variant: the per-return fire operand is pre-gathered
    for ALL returns by one XLA gather on device (HBM-resident
    ``[R_pad, S, W*S]``) and streamed through the pallas pipeline —
    the in-kernel gather (and its SMEM scalar reads) disappear; the
    DMA engine does the fetch while the MXU chain runs."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_blocks = R_pad // B

    def kernel(ret_slot_ref, G_ref, R0_ref, ckpt_ref, final_ref, R_scr):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            R_scr[:] = R0_ref[:]

        ckpt_ref[0] = R_scr[:]

        def do_return(k, _):
            G_all = G_ref[k]
            if g_dtype != "float32":
                G_all = G_all.astype(jnp.float32)
            R = R_scr[:]
            for _p in range(n_pass):
                R = fire(R, G_all, W, M, S)
            R_scr[:] = _proj_blend(R, ret_slot_ref[k], W, M, S, counts)
            return 0

        jax.lax.fori_loop(0, B, do_return, 0)

        @pl.when(step == n_blocks - 1)
        def _finish():
            final_ref[:] = R_scr[:]

    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((B,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((B, S, W * S), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((M, S), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, M, S), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((M, S), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_blocks, M, S), jnp.float32),
            jax.ShapeDtypeStruct((M, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((M, S), jnp.float32),
        ],
    )

    def run(ret_slot, slot_ops, P, PJ, R0):
        so = slot_ops.astype(jnp.int32).reshape(R_pad, W)
        o = jnp.where(so < 0, O1 - 1, so)
        G = P[o]                                   # [R_pad, W, S, S]
        G = jnp.transpose(G, (0, 2, 1, 3)).reshape(R_pad, S, W * S)
        G = G.astype(g_dtype)
        return call(ret_slot.astype(jnp.int32), G, R0)

    return jax.jit(run)


VARIANTS = {
    # name: (fire, proj, counts, unroll, n_pass or None=min(W,5))
    "v2-bool-blend": (_fire_bool, "blend", False, 1, None),
    "cnt-tree-blend": (_fire_counts_tree, "blend", True, 1, None),
    "maxnc-blend": (_fire_maxnc, "blend", True, 1, None),
    "bool-matmulproj": (_fire_bool, "matmul", False, 1, None),
    "bool-stream": (_fire_bool, "stream", False, 1, None),
    "maxnc-stream": (_fire_maxnc, "stream", True, 1, None),
    "bool-stream-i8": (_fire_bool, "stream-i8", False, 1, None),
    "v2-p4": (_fire_bool, "blend", False, 1, 4),
    "v2-p3": (_fire_bool, "blend", False, 1, 3),
    "v2-p2": (_fire_bool, "blend", False, 1, 2),
    "alt-p2": ((_fire_bool, _fire_bool_rev), "blend", False, 1, 2),
    "alt-p3": ((_fire_bool, _fire_bool_rev), "blend", False, 1, 3),
    "alt-p4": ((_fire_bool, _fire_bool_rev), "blend", False, 1, 4),
    # exact per-return pass gating: pending count c_r bounds closure
    # depth, so n_pass unconditional passes + (5 - n_pass) passes under
    # an untaken-free pl.when for the rare c_r > n_pass returns
    "cgate4+1": (_fire_bool, "blend", False, 1, 4, (1,)),
    "cgate3+2": (_fire_bool, "blend", False, 1, 3, (2,)),
    "cgate2+3": (_fire_bool, "blend", False, 1, 2, (3,)),
    "cgate3+1+1": (_fire_bool, "blend", False, 1, 3, (1, 1)),
    "cgate2+1+1+1": (_fire_bool, "blend", False, 1, 2, (1, 1, 1)),
    "cgate2+2+1": (_fire_bool, "blend", False, 1, 2, (2, 1)),
    "cgate1+1+1+1+1": (_fire_bool, "blend", False, 1, 1, (1, 1, 1, 1)),
    "cgate-ladder-u2": (_fire_bool, "blend", False, 2, 1, (1, 1, 1, 1)),
    "cgate-ladder-alt": ((_fire_bool, _fire_bool_rev), "blend", False, 1,
                         1, (1, 1, 1, 1)),
}


def body_sweep(ops: int, repeat: int, record: bool) -> int:
    """Post-hoc KERNEL-BODY sweep (any backend, incl. XLA:CPU): the
    word-packed walk (``reach_word``) vs the dense einsum walk on one
    generated cas history, verdict-asserted identical, winner
    PERSISTED as the autotune ``walk`` entry route selection
    (``reach.check_packed``) consults. The Pallas variant ladder
    below stays the on-chip microscope; this is the cross-body
    decision the table exists for."""
    import json as _json

    import numpy as np

    from jepsen_tpu import fixtures, models
    from jepsen_tpu.checkers import autotune, events as ev
    from jepsen_tpu.checkers import reach, reach_word
    from jepsen_tpu.history import pack

    hist = fixtures.gen_history("cas", n_ops=ops, processes=5,
                                seed=42)
    model = models.cas_register()
    packed = pack(hist)
    memo, stream, _T, S_pad, M = reach._prep(
        model, packed, max_states=100_000, max_slots=20,
        max_dense=1 << 22)
    W = max(stream.W, 1)
    rs = ev.returns_view(stream)
    n = rs.n_returns

    def _one(body: str):
        import os as _os
        env = "JEPSEN_TPU_WORD_POSTHOC"
        no_word = "JEPSEN_TPU_NO_WORD_WALK"
        old = {k: _os.environ.pop(k, None) for k in (env, no_word)}
        try:
            if body == "word":
                _os.environ[env] = "1"
            else:
                _os.environ[no_word] = "1"
            res = reach.check_packed(model, packed)   # warm
            best = float("inf")
            for _ in range(max(1, repeat)):
                t0 = time.monotonic()
                res = reach.check_packed(model, packed)
                best = min(best, time.monotonic() - t0)
            return res, best
        finally:
            for k, v in old.items():
                _os.environ.pop(k, None)
                if v is not None:
                    _os.environ[k] = v

    res_w, t_word = _one("word")
    res_d, t_dense = _one("dense")
    assert res_w["valid"] == res_d["valid"], (res_w, res_d)
    winner = "word" if t_word <= t_dense else "dense"
    row = {"geometry": {"S": memo.n_states, "W": W, "M": M,
                        "returns": int(n)},
           "word_s": round(t_word, 4), "dense_s": round(t_dense, 4),
           "winner": winner,
           "speedup": round(t_dense / max(t_word, 1e-9), 2),
           "word_engine": res_w.get("engine"),
           "dense_engine": res_d.get("engine")}
    if record:
        row["recorded"] = autotune.record(
            "walk", autotune.walk_key(memo.n_states, W, M, n), winner,
            metric=n / max(min(t_word, t_dense), 1e-9),
            detail={"word_s": row["word_s"],
                    "dense_s": row["dense_s"]})
    print(_json.dumps(row), flush=True)
    return 0


def _pipe_drive(model, batches, K: int):
    """Process ``batches`` through the serve-lane window discipline at
    in-flight depth K: stage batch b+1 while b walks, collect ready
    predecessors, block on the oldest at a full window. K=1 is the
    blocking degenerate (``check_many`` per batch) — the bit-identity
    reference. Returns (results per batch, wall seconds)."""
    from collections import deque

    from jepsen_tpu.checkers import reach

    os.environ["JEPSEN_TPU_PIPE_K"] = str(K)
    try:
        t0 = time.monotonic()
        out = [None] * len(batches)
        window: deque = deque()
        for bi, b in enumerate(batches):
            st = reach.stage_check_many(model, b) if K > 1 else None
            if st is None:
                while window:           # FIFO: drain before blocking
                    i, hd = window.popleft()
                    out[i] = hd.collect()
                out[bi] = reach.check_many(model, b)
                continue
            window.append((bi, st))
            while window and window[0][1].ready():
                i, hd = window.popleft()
                out[i] = hd.collect()
            while len(window) >= K:
                i, hd = window.popleft()
                out[i] = hd.collect()
        while window:
            i, hd = window.popleft()
            out[i] = hd.collect()
        return out, time.monotonic() - t0
    finally:
        os.environ.pop("JEPSEN_TPU_PIPE_K", None)


def pipeline_sweep(repeat: int, record: bool) -> int:
    """ISSUE 20 satellite: measure the serve-lane in-flight depth
    K ∈ {1,2,4,8} per geometry bucket with the REAL stage/collect
    protocol (``reach.stage_check_many`` → window → collect), assert
    every depth's verdicts bit-identical to the K=1 blocking
    reference, and persist winners in the autotune table — the
    per-bucket detail rows plus the aggregate ``pipeline|serve``
    entry :func:`dispatch_core.pipeline_k` consults (staleness-guarded
    like every other entry: a winner measured under another XLA is
    ignored at lookup)."""
    import json as _json

    from jepsen_tpu import fixtures, models
    from jepsen_tpu.checkers import autotune, events as ev
    from jepsen_tpu.checkers import reach
    from jepsen_tpu.history import pack

    model = models.cas_register()
    ks = (1, 2, 4, 8)
    overall: dict = {}
    # geometry buckets: return-count and slot-width vary with history
    # length and process count (S is the model's)
    shapes = [(240, 3), (900, 4), (2400, 5)]
    for n_ops, procs in shapes:
        batches = [[pack(fixtures.gen_history(
            "cas", n_ops=n_ops + 40 * j, processes=procs,
            seed=17 * bi + j))
            for j in range(4)] for bi in range(6)]
        memo, stream, _T, _S_pad, M = reach._prep(
            model, batches[0][0], max_states=100_000, max_slots=20,
            max_dense=1 << 22)
        W = max(stream.W, 1)
        rets = ev.returns_view(stream).n_returns
        key = autotune.walk_key(memo.n_states, W, M, rets)
        ref, _ = _pipe_drive(model, batches, 1)       # warm + reference
        walls = {}
        for K in ks:
            best = float("inf")
            for _ in range(max(1, repeat)):
                out, wall = _pipe_drive(model, batches, K)
                for rb, ob in zip(ref, out):
                    for r, o in zip(rb, ob):
                        assert r["valid"] == o["valid"], (K, r, o)
                best = min(best, wall)
            walls[K] = round(best, 4)
        bestK = min(ks, key=lambda K: walls[K])
        row = {"bucket": key, "walls_s": {str(K): walls[K] for K in ks},
               "winner_k": bestK,
               "speedup_vs_k1": round(
                   walls[1] / max(walls[bestK], 1e-9), 2)}
        if record:
            row["recorded"] = autotune.record(
                "pipeline", key, str(bestK),
                metric=1.0 / max(walls[bestK], 1e-9),
                detail={"walls_s": row["walls_s"]})
        overall[key] = (bestK, walls[1] / max(walls[bestK], 1e-9))
        print(_json.dumps(row), flush=True)
    # the aggregate serve-lane entry pipeline_k("serve") consults:
    # the depth that wins the most buckets (speedup breaks ties)
    votes: dict = {}
    for k, gain in overall.values():
        n, g = votes.get(k, (0, 0.0))
        votes[k] = (n + 1, g + gain)
    serve_k = max(votes, key=lambda k: votes[k])
    out = {"bucket": "serve", "winner_k": serve_k,
           "buckets": {b: k for b, (k, _g) in overall.items()}}
    if record:
        out["recorded"] = autotune.record(
            "pipeline", "serve", str(serve_k),
            metric=sum(g for _n, g in votes.values()),
            detail={"votes": {str(k): n for k, (n, _g)
                              in votes.items()}})
    print(_json.dumps(out), flush=True)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=100_000)
    ap.add_argument("--variants", default=",".join(VARIANTS))
    ap.add_argument("--repeat", type=int, default=2)
    ap.add_argument("--bodies", action="store_true",
                    help="sweep the word-packed vs dense post-hoc "
                         "kernel BODIES (any backend) and persist "
                         "the winner in the autotune table instead "
                         "of running the Pallas variant ladder")
    ap.add_argument("--pipeline", action="store_true",
                    help="sweep the serve-lane in-flight depth "
                         "K in {1,2,4,8} per geometry bucket over the "
                         "real stage/collect protocol and persist "
                         "winners (kind 'pipeline') in the autotune "
                         "table")
    ap.add_argument("--no-record", action="store_true",
                    help="with --bodies/--pipeline: measure only, do "
                         "not write the autotune table")
    args = ap.parse_args()
    if args.pipeline:
        return pipeline_sweep(args.repeat, record=not args.no_record)
    if args.bodies:
        return body_sweep(args.ops, args.repeat,
                          record=not args.no_record)

    import jax
    from jepsen_tpu import fixtures, models
    from jepsen_tpu.history import pack
    from jepsen_tpu.checkers import events as ev
    from jepsen_tpu.checkers import reach, reach_lane

    hist = fixtures.gen_history("cas", n_ops=args.ops, processes=5,
                                seed=42)
    model = models.cas_register()
    packed = pack(hist)
    memo, stream, _T, S, M = reach._prep(
        model, packed, max_states=100_000, max_slots=20,
        max_dense=1 << 22)
    rs = ev.returns_view(stream)
    P_np = reach._build_P(memo, S)
    R0 = np.zeros((S, M), bool)
    R0[0, 0] = True
    geom, _, _, host_args = reach_lane.pack_operands(
        P_np, rs.ret_slot, rs.slot_ops, R0)
    B, W, M, S, O1, R_pad = geom
    R_real = int(rs.ret_slot.shape[0])
    print(f"geometry B={B} W={W} M={M} S={S} O1={O1} R_pad={R_pad} "
          f"returns={R_real}")
    # pack_operands layout (round 4): (ret_slot, slot_ops, P, R0) —
    # pend is derived on device. Insert the projection table the
    # matmul ablation variants expect between slot_ops and P.
    ret_slot_h, slot_ops_h, P_h, R0_h = host_args
    if R0_h.dtype == np.uint8:
        # round-6 diet: pack_operands bit-packs the seed by default;
        # the ablation kernels predate the in-jit unpack, so
        # re-materialize the dense f32 seed they expect
        from jepsen_tpu.checkers import transfer
        R0_h = transfer.unpack_bool_host(R0_h, M * S) \
            .reshape(M, S).astype(np.float32)
    host_args = (ret_slot_h, slot_ops_h, P_h,
                 _proj_table_np(W, M), R0_h)
    dargs = jax.device_put(host_args)
    names = args.variants.split(",")
    runs = {}
    for name in names:
        spec = VARIANTS[name]
        fire, proj, counts, unroll, np_ = spec[:5]
        cgate = spec[5] if len(spec) > 5 else 0
        np_ = min(W, 5) if np_ is None else np_
        try:
            if proj == "stream":
                runs[name] = make_call_stream(B, W, M, S, O1, R_pad,
                                              np_, fire, counts)
            elif proj == "stream-i8":
                runs[name] = make_call_stream(B, W, M, S, O1, R_pad,
                                              np_, fire, counts,
                                              g_dtype="int8")
            else:
                runs[name] = make_call(B, W, M, S, O1, R_pad,
                                       np_, fire, proj, counts,
                                       unroll, cgate)
        except Exception as e:                          # noqa: BLE001
            print(f"{name:22s} BUILD FAILED: {type(e).__name__}: "
                  f"{str(e)[:120]}")
    # interleaved rounds so tunnel/chip drift hits every variant alike
    best = {n: float("inf") for n in runs}
    for _ in range(args.repeat):
        for name, run in runs.items():
            try:
                best[name] = min(best[name], _probe(run, dargs))
            except Exception as e:                      # noqa: BLE001
                print(f"{name:22s} RUN FAILED: {type(e).__name__}: "
                      f"{str(e)[:120]}")
                best[name] = float("nan")
                runs[name] = None
        runs = {n: r for n, r in runs.items() if r is not None}
    ref_final = None
    for name in names:
        if name not in best or best[name] != best[name]:
            continue
        alive = False
        if name in runs:
            final = np.asarray(runs[name](*dargs)[1]) > 0
            alive = bool(final.any())
            if ref_final is None:
                ref_final = final
            agree = bool((final == ref_final).all())
        else:
            agree = False
        print(f"{name:22s} {best[name]*1e3:8.1f} ms "
              f"{best[name]/max(R_real,1)*1e9:7.0f} ns/ret  "
              f"match={agree} alive={alive}")


if __name__ == "__main__":
    main()
