"""Lockstep batch-width sweep: measure the per-history-return cost of
``reach.check_batch`` as the lockstep width H grows (8/16/32/...), now
that the block size adapts to keep the slot_ops SMEM window under the
chip's 1 MB (``reach_batch._adaptive_block``). Reports e2e time plus a
dispatch-slope kernel figure per width so the "step cost is flat in H"
claim (BASELINE.md round-4 batch rung) can be extended or refuted at
H=32 without guessing.

``--ragged`` instead sweeps a mixed-length independent-keys batch
through the bucketed lane packer (``reach_batch.plan_buckets``):
reports each lockstep group's geometry and pack efficiency (real vs
padded returns), against the naive single-group packing that pads
every key to the longest — the quantity the ISSUE-1 bucketing exists
to fix.

Usage: python tools/batch_width.py [--ops 100000] [--widths 8,16,32]
       [--ragged] [--keys 12]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def ragged_sweep(total_ops: int, keys: int, repeat: int) -> int:
    """Bucketed vs naive packing on a ragged independent-keys batch:
    plan, per-group geometry, pack efficiency, and (when the lockstep
    lane runs) measured e2e through ``reach.check_many``."""
    from jepsen_tpu import fixtures, models
    from jepsen_tpu.checkers import reach, reach_batch

    model = models.cas_register()
    from bench import _ragged_lengths
    lens = _ragged_lengths(total_ops, keys=keys)
    packeds = [fixtures.gen_packed("cas", n_ops=n, seed=100 + i)
               for i, n in enumerate(lens)]
    live = list(range(len(packeds)))
    u = reach._union_prep(model, packeds, live, 100_000, 20)
    if u is None:
        print(json.dumps({"error": "union prep failed"}))
        return 1
    (_memo_u, _S_pad, _P, W, _M, _ret_flat, _ops_flat, _key_W, key_R,
     *_rest) = u
    R_lens = [int(r) for r in key_R]
    groups = reach_batch.plan_buckets(R_lens, W)

    def _padded(groups_):
        tot = 0
        for g in groups_:
            H = len(g)
            _B, R_pad = reach_batch.group_geom(
                max(R_lens[k] for k in g), H, W)
            tot += H * R_pad
        return tot

    real = sum(R_lens)
    bucketed = _padded(groups)
    naive = _padded([live])             # one group, longest pads all
    plan = {
        "keys": keys, "lens": lens, "W": W,
        "groups": [[R_lens[k] for k in g] for g in groups],
        "real_returns": real,
        "bucketed_padded": bucketed,
        "naive_padded": naive,
        "bucketed_efficiency": round(real / max(bucketed, 1), 4),
        "naive_efficiency": round(real / max(naive, 1), 4),
    }
    print(json.dumps(plan), flush=True)
    diag: dict = {}
    res = reach.check_many(model, packeds, diag=diag)   # warm
    engines = sorted({r["engine"] for r in res})
    times = []
    for _ in range(max(1, repeat)):
        t0 = time.monotonic()
        reach.check_many(model, packeds)
        times.append(time.monotonic() - t0)
    best = min(times)
    total = sum(lens)       # actual generated ops (per-key floor can
    print(json.dumps({      # push the sum past the requested total)
        "engine": engines, "e2e_s": round(best, 3),
        "agg_ops_s": round(total / best),
        "pack_efficiency": diag.get("pack_efficiency"),
        "kernel_cache": diag.get("kernel_cache"),
        "per_bucket": diag.get("groups", []),
    }), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=100_000)
    ap.add_argument("--widths", default="8,16,32")
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--ragged", action="store_true",
                    help="sweep a mixed-length key batch through the "
                         "bucketed lane packer instead of the uniform "
                         "width ladder")
    ap.add_argument("--keys", type=int, default=12,
                    help="key count for --ragged")
    ap.add_argument("--record", action="store_true",
                    help="persist the winning lockstep width in the "
                         "autotune table (the ``group`` winner the "
                         "facade consults before the built-in "
                         "default) — H=32-beats-H=64 folklore, "
                         "measured instead of re-derived")
    args = ap.parse_args()
    if args.ragged:
        return ragged_sweep(args.ops, args.keys, args.repeat)
    widths = [int(w) for w in args.widths.split(",")]
    H_max = max(widths)

    import numpy as np

    from jepsen_tpu import fixtures, models
    from jepsen_tpu.checkers import reach, reach_batch

    model = models.cas_register()
    packeds = [fixtures.gen_packed("cas", n_ops=args.ops, seed=100 + i)
               for i in range(H_max)]
    out = []
    for H in widths:
        sub = packeds[:H]
        live = list(range(H))
        u = reach._union_prep(model, sub, live, 100_000, 20)
        if u is None:
            print(json.dumps({"H": H, "error": "union prep failed"}))
            continue
        (memo_u, S_pad, P, W, M, ret_flat, ops_flat, key_W, key_R,
         offsets, *_rest) = u
        rets = [ret_flat[offsets[k]:offsets[k + 1]] for k in live]
        ops = [ops_flat[offsets[k]:offsets[k + 1]] for k in live]
        geom, host_args, R_lens = reach_batch.pack_batch_operands(
            P, rets, ops, M)
        B = geom[0]
        n_pass = min(geom[1], reach_batch._FAST_PASSES)
        # e2e (best of repeat), through the public entry
        times = []
        for _ in range(max(1, args.repeat)):
            t0 = time.monotonic()
            res = reach.check_batch(model, sub, group=H)
            times.append(time.monotonic() - t0)
        assert all(r["valid"] for r in res), res
        e2e = min(times)
        # kernel dispatch slope on cached device segments
        dsegs: dict = {}
        _, final = reach_batch._pipe_walk_b(host_args, geom, n_pass,
                                            False, dsegs)
        _ = np.asarray(final)
        t0 = time.monotonic()
        _, final = reach_batch._pipe_walk_b(host_args, geom, n_pass,
                                            False, dsegs)
        _ = np.asarray(final)
        one = time.monotonic() - t0
        K = 4
        t0 = time.monotonic()
        for _ in range(K):
            _, final = reach_batch._pipe_walk_b(host_args, geom, n_pass,
                                                False, dsegs)
        _ = np.asarray(final)
        many = time.monotonic() - t0
        kernel_s = max(0.0, (many - one) / (K - 1))
        hist_returns = int(sum(R_lens))
        steps = geom[6]                       # R_pad lockstep steps
        row = {
            "H": H, "B": B, "W": geom[1], "M": M, "S": geom[3],
            "e2e_s": round(e2e, 3),
            "agg_ops_s": round(args.ops * H / e2e),
            "kernel_s": round(kernel_s, 4),
            "ns_per_step": round(kernel_s / max(steps, 1) * 1e9),
            "ns_per_history_return": round(
                kernel_s / max(hist_returns, 1) * 1e9, 1),
        }
        out.append(row)
        print(json.dumps(row), flush=True)
    if args.record and out:
        from jepsen_tpu.checkers import autotune
        best = max(out, key=lambda r: r["agg_ops_s"])
        path = autotune.record(
            "group", "default", str(best["H"]),
            metric=float(best["agg_ops_s"]),
            detail={"widths": {str(r["H"]): r["agg_ops_s"]
                               for r in out}})
        print(json.dumps({"recorded": path, "group": best["H"]}),
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
