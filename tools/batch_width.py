"""Lockstep batch-width sweep: measure the per-history-return cost of
``reach.check_batch`` as the lockstep width H grows (8/16/32/...), now
that the block size adapts to keep the slot_ops SMEM window under the
chip's 1 MB (``reach_batch._adaptive_block``). Reports e2e time plus a
dispatch-slope kernel figure per width so the "step cost is flat in H"
claim (BASELINE.md round-4 batch rung) can be extended or refuted at
H=32 without guessing.

Usage: python tools/batch_width.py [--ops 100000] [--widths 8,16,32]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=100_000)
    ap.add_argument("--widths", default="8,16,32")
    ap.add_argument("--repeat", type=int, default=3)
    args = ap.parse_args()
    widths = [int(w) for w in args.widths.split(",")]
    H_max = max(widths)

    import numpy as np

    from jepsen_tpu import fixtures, models
    from jepsen_tpu.checkers import reach, reach_batch

    model = models.cas_register()
    packeds = [fixtures.gen_packed("cas", n_ops=args.ops, seed=100 + i)
               for i in range(H_max)]
    out = []
    for H in widths:
        sub = packeds[:H]
        live = list(range(H))
        u = reach._union_prep(model, sub, live, 100_000, 20)
        if u is None:
            print(json.dumps({"H": H, "error": "union prep failed"}))
            continue
        (memo_u, S_pad, P, W, M, ret_flat, ops_flat, key_W, key_R,
         offsets, *_rest) = u
        rets = [ret_flat[offsets[k]:offsets[k + 1]] for k in live]
        ops = [ops_flat[offsets[k]:offsets[k + 1]] for k in live]
        geom, host_args, R_lens = reach_batch.pack_batch_operands(
            P, rets, ops, M)
        B = geom[0]
        n_pass = min(geom[1], reach_batch._FAST_PASSES)
        # e2e (best of repeat), through the public entry
        times = []
        for _ in range(max(1, args.repeat)):
            t0 = time.monotonic()
            res = reach.check_batch(model, sub, group=H)
            times.append(time.monotonic() - t0)
        assert all(r["valid"] for r in res), res
        e2e = min(times)
        # kernel dispatch slope on cached device segments
        dsegs: dict = {}
        _, final = reach_batch._pipe_walk_b(host_args, geom, n_pass,
                                            False, dsegs)
        _ = np.asarray(final)
        t0 = time.monotonic()
        _, final = reach_batch._pipe_walk_b(host_args, geom, n_pass,
                                            False, dsegs)
        _ = np.asarray(final)
        one = time.monotonic() - t0
        K = 4
        t0 = time.monotonic()
        for _ in range(K):
            _, final = reach_batch._pipe_walk_b(host_args, geom, n_pass,
                                                False, dsegs)
        _ = np.asarray(final)
        many = time.monotonic() - t0
        kernel_s = max(0.0, (many - one) / (K - 1))
        hist_returns = int(sum(R_lens))
        steps = geom[6]                       # R_pad lockstep steps
        row = {
            "H": H, "B": B, "W": geom[1], "M": M, "S": geom[3],
            "e2e_s": round(e2e, 3),
            "agg_ops_s": round(args.ops * H / e2e),
            "kernel_s": round(kernel_s, 4),
            "ns_per_step": round(kernel_s / max(steps, 1) * 1e9),
            "ns_per_history_return": round(
                kernel_s / max(hist_returns, 1) * 1e9, 1),
        }
        out.append(row)
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
