"""jtlint CLI — the AST-driven invariant analyzer as a CI gate.

Five passes over the tree (docs/ANALYSIS.md): donation aliasing (the
PR-10 reuse-after-donation bug class), silent ``except`` fallbacks in
``checkers/``/``serve/``/``txn/``, the ``JEPSEN_TPU_*`` env-gate
registry + doc cross-check, obs counter/doc drift, and declared lock
discipline (``_GUARDED_BY``).

Pure stdlib ``ast`` — no jax import, so the CI ``lint`` job needs no
accelerator stack and finishes in seconds. Same budget-file-plus-guard
shape as ``tools/transfer_guard.py``: accepted pre-existing findings
live in the checked-in ``data/lint_baseline.json`` (adds show up in
review), one-off sites carry inline ``# jtlint: ok <pass>``.

Usage:
    python tools/lint.py --strict                 # the CI gate
    python tools/lint.py --passes donation        # one pass
    python tools/lint.py --emit-env-registry      # refresh data/env_gates.json
    python tools/lint.py --write-baseline         # accept current findings
"""
from __future__ import annotations

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

from jepsen_tpu.analysis.core import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
