"""Self-nemesis chaos harness: Jepsen's discipline pointed at our own
checker-as-a-service daemon.

Starts a REAL ``check-serve`` daemon subprocess armed with a seeded
fault schedule (``jepsen_tpu/serve/faults.py`` via
``JEPSEN_TPU_SERVE_FAULTS``), drives known-ground-truth load at it
over real HTTP, SIGKILLs the process mid-load, restarts it on the
same store root, and asserts the invariants we demand of etcd on
ourselves:

1. **No lost acknowledgements.** Every request that got its 202 — in
   particular those queued/in-flight at the SIGKILL — reaches a
   terminal state after the journal replay, under its original id.
2. **No divergent verdicts.** Every ``done`` verdict equals the
   known ground truth AND the standalone facade differential
   recomputed in this process (witness op included for violations) —
   through injected dispatch crashes, device outages, persist
   failures, clock jumps, bisect retries, and degraded host-side
   serving.
3. **No silent faults.** Every injected fault type that fired shows
   up in the obs ledger (``serve.fault.*`` counters) WITH its
   visible consequence (``serve.retry.*``, ``serve.quarantined``,
   breaker transitions, ``serve-persist`` fallback) — and every
   scheduled core fault actually fired.
4. **Poison isolation.** The request from the poison tenant (its
   dispatch crashes every route) is quarantined with a structured
   500 while every co-tenant of its coalesced groups completes.
5. **Recovery.** The journal fully drains (no pending entries on
   disk, ``/healthz`` agrees) and the daemon ends non-degraded
   (breaker closed) — and the report carries the measured
   recovery-time-to-first-verdict across the kill.

Usage::

    python tools/chaos.py --quick        # CI: one dispatch fault +
                                         # one SIGKILL/restart
    python tools/chaos.py --seed 7       # full gauntlet: dispatch,
                                         # device-outage (breaker),
                                         # persist, clock-jump,
                                         # poison, SIGKILL

Exit 0 iff every invariant held. The JSON report goes to stdout.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# one JSON-over-HTTP client per toolbox, not per tool: the loadgen's
# helpers already preserve structured error bodies (the quarantined
# 500) and classify transport failures as code -1
import loadgen as _lg  # noqa: E402

_TERMINAL = ("done", "timeout", "cancelled", "quarantined")

_get = _lg._get


def _post(url: str, payload: Dict) -> Tuple[int, Dict]:
    return _lg._post(url, json.dumps(payload).encode())


def _wait_ready(url: str, timeout: float = 120.0) -> bool:
    return _lg.wait_ready(url, timeout=timeout)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- daemon process management -------------------------------------------

class DaemonProc:
    def __init__(self, store_root: str, *, faults_env: str = "",
                 log_path: str, breaker_threshold: int = 3,
                 breaker_cooldown: float = 1.0,
                 group: int = 8,
                 extra_args: Optional[List[str]] = None) -> None:
        self.port = _free_port()
        self.url = f"http://127.0.0.1:{self.port}"
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if faults_env:
            env["JEPSEN_TPU_SERVE_FAULTS"] = faults_env
        else:
            env.pop("JEPSEN_TPU_SERVE_FAULTS", None)
        self.log = open(log_path, "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "jepsen_tpu", "check-serve",
             "--port", str(self.port), "--store-root", store_root,
             "--group", str(group),
             "--breaker-threshold", str(breaker_threshold),
             "--breaker-cooldown", str(breaker_cooldown)]
            + list(extra_args or []),
            cwd=REPO, env=env, stdout=self.log, stderr=self.log)

    def sigkill(self) -> None:
        # the hard crash: no drain, no atexit, no flush — exactly the
        # fault the durable journal exists for
        os.kill(self.proc.pid, signal.SIGKILL)
        self.proc.wait(30)
        self.log.close()

    def sigterm(self) -> int:
        self.proc.send_signal(signal.SIGTERM)
        rc = self.proc.wait(120)
        self.log.close()
        return rc


# -- workload ------------------------------------------------------------

def build_cases(*, seed: int, n: int, sizes, violation_frac: float,
                deadline_frac: float = 0.0,
                tenant_prefix: str = "chaos") -> List[Dict]:
    """Known-ground-truth payloads: each case carries its expected
    verdict and its ops (for the standalone differential)."""
    from jepsen_tpu import fixtures
    cases = []
    for i in range(n):
        n_ops = sizes[i % len(sizes)]
        hist = fixtures.gen_history("cas", n_ops=n_ops, processes=3,
                                    seed=seed + i)
        expect = True
        if (i * 997 % 101) / 101.0 < violation_frac:
            hist = fixtures.corrupt(hist, seed=seed + i)
            expect = False
        payload: Dict[str, Any] = {
            "model": "cas-register",
            "tenant": f"{tenant_prefix}-{i % 3}",
            "history": [op.to_dict() for op in hist],
            "idempotency-key": f"{tenant_prefix}-key-{seed}-{i}",
        }
        if deadline_frac and (i * 31 % 17) / 17.0 < deadline_frac:
            # generous deadline: only an injected clock JUMP (not real
            # latency) can expire it
            payload["timeout-s"] = 600.0
        cases.append({"payload": payload, "expect": expect,
                      "ops": hist, "id": None, "final": None})
    return cases


def submit_cases(url: str, cases: List[Dict]) -> int:
    n = 0
    for c in cases:
        code, resp = _post(url, c["payload"])
        if code == 202:
            c["id"] = resp["id"]
            n += 1
        else:
            c["final"] = {"status": f"error-{code}", "resp": resp}
    return n


def poll_terminal(url: str, cases: List[Dict],
                  timeout: float = 300.0) -> Optional[float]:
    """Poll every admitted case to a terminal state; returns the
    monotonic instant the first ``done`` verdict was observed (the
    recovery clock's far edge), or None."""
    first_done = None
    end = time.monotonic() + timeout
    pending = [c for c in cases if c["id"] and c["final"] is None]
    while pending and time.monotonic() < end:
        for c in list(pending):
            code, st = _get(url, f"/check/{c['id']}")
            if code in (200, 500) and st.get("status") in _TERMINAL:
                c["final"] = st
                if st["status"] == "done" and first_done is None:
                    first_done = time.monotonic()
                pending.remove(c)
        time.sleep(0.1)
    return first_done


# -- the harness ---------------------------------------------------------

def run_chaos(opts: Dict[str, Any]) -> Dict[str, Any]:
    quick = bool(opts.get("quick"))
    seed = int(opts.get("seed", 7))
    keep_store = bool(opts.get("keep_store"))
    root = opts.get("store_root") or tempfile.mkdtemp(
        prefix="jepsen-chaos-")
    os.makedirs(root, exist_ok=True)
    log_path = os.path.join(root, "chaos-daemon.log")
    report: Dict[str, Any] = {"store_root": root, "seed": seed,
                              "quick": quick, "violations": []}

    def violate(msg: str) -> None:
        report["violations"].append(msg)

    # seeded fault schedule: invocation indices derived from the seed,
    # kept low so short CI runs reach them
    import random
    rng = random.Random(seed)
    if quick:
        schedule = f"dispatch@{rng.randint(2, 3)}"
        expected_faults = ["dispatch"]
        poison = False
    else:
        schedule = ";".join([
            f"dispatch@{rng.randint(2, 4)}",
            f"device@{rng.randint(5, 7)}x{opts.get('device_burst', 6)}",
            f"persist@{rng.randint(1, 3)}",
            f"clock-jump@{rng.randint(6, 9)}:3600",
            "poison=chaos-poison",
        ])
        expected_faults = ["dispatch", "device", "persist",
                           "clock_jump", "poison"]
        poison = True
    report["fault_schedule"] = schedule

    n_wave1 = 6 if quick else 14
    wave1 = build_cases(seed=seed, n=n_wave1,
                        sizes=[8, 12] if quick else [8, 12, 16],
                        violation_frac=0.3,
                        deadline_frac=0.0 if quick else 0.25)
    poison_case = None
    if poison:
        poison_case = build_cases(seed=seed + 500, n=1, sizes=[8],
                                  violation_frac=0.0,
                                  tenant_prefix="chaos-poison")[0]
        poison_case["payload"]["tenant"] = "chaos-poison"

    # ---- phase 1: armed daemon, drive the fault gauntlet ----
    d1 = DaemonProc(root, faults_env=schedule, log_path=log_path)
    try:
        if not _wait_ready(d1.url):
            violate("daemon 1 never became ready")
            return report
        submit_cases(d1.url, wave1)
        if poison_case is not None:
            submit_cases(d1.url, [poison_case])
        poll_terminal(d1.url, wave1, timeout=600)
        if poison_case is not None:
            poll_terminal(d1.url, [poison_case], timeout=120)

        # keep feeding filler dispatches until every scheduled fault's
        # invocation index has been reached (bounded)
        def fault_counters() -> Dict[str, float]:
            code, stats = _get(d1.url, "/stats")
            if code != 200:
                return {}
            return {k: v for k, v in stats.get("counters", {}).items()
                    if k.startswith("serve.fault.")}
        filler_budget = 12
        while filler_budget > 0:
            fc = fault_counters()
            missing = [f for f in expected_faults
                       if fc.get(f"serve.fault.{f}", 0) < 1]
            if not missing:
                break
            filler = build_cases(seed=seed + 900 + filler_budget, n=2,
                                 sizes=[8], violation_frac=0.0,
                                 tenant_prefix="filler")
            submit_cases(d1.url, filler)
            poll_terminal(d1.url, filler, timeout=120)
            wave1.extend(filler)
            filler_budget -= 1
        report["fault_counters"] = fault_counters()
        for f in expected_faults:
            if report["fault_counters"].get(f"serve.fault.{f}", 0) < 1:
                violate(f"scheduled fault {f!r} never fired")

        # fault CONSEQUENCES must be in the ledger too (no silent
        # recovery): scrape the counters the recovery machinery bumps
        code, stats1 = _get(d1.url, "/stats")
        c1 = stats1.get("counters", {}) if code == 200 else {}
        report["pre_kill_counters"] = {
            k: v for k, v in c1.items()
            if k.startswith(("serve.retry.", "serve.quarantined",
                             "serve.breaker.", "serve.journal."))}
        if c1.get("serve.retry.attempts", 0) < 1:
            violate("dispatch fault fired but no retry was recorded")
        if not quick:
            persist_falls = [k for k in c1
                            if k.startswith("engine.fallback."
                                            "serve-persist.")]
            if not persist_falls:
                violate("persist fault fired but no serve-persist "
                        "fallback recorded")
        if poison_case is not None:
            st = poison_case["final"] or {}
            if st.get("status") != "quarantined":
                violate(f"poison member not quarantined: {st}")
            if c1.get("serve.quarantined", 0) < 1:
                violate("no serve.quarantined counter")

        # ---- long-lived streaming session: opened + partially
        # appended BEFORE the SIGKILL; it must ride the crash — same
        # id, journaled appends replayed, frontier re-derived — and
        # reach the same final verdict as the standalone facade ----
        from jepsen_tpu import fixtures as _fx
        sess_hist = _fx.gen_history("cas", n_ops=72, processes=3,
                                    seed=seed + 2000)
        sess_blocks = [sess_hist[i:i + 12]
                       for i in range(0, len(sess_hist), 12)]
        n_pre = len(sess_blocks) // 2
        sess_id = None
        code, resp = _lg._post_json(d1.url, "/session",
                                    {"model": "cas-register",
                                     "tenant": "chaos-sess"})
        if code != 201:
            violate(f"session open failed: {code} {resp}")
        else:
            sess_id = resp["session"]
            for seq in range(1, n_pre + 1):
                code, r = _lg._post_json(
                    d1.url, f"/session/{sess_id}/append",
                    {"history": [op.to_dict()
                                 for op in sess_blocks[seq - 1]],
                     "seq": seq, "wait-s": 120})
                if code != 200 or r.get("valid-so-far") is not True:
                    violate(f"pre-kill session append {seq} bad: "
                            f"{code} {r}")
        report["session_id"] = sess_id
        report["session_pre_kill_appends"] = n_pre

        # ---- phase 2: wave 2 posts, then SIGKILL mid-load ----
        wave2 = build_cases(seed=seed + 1000, n=4 if quick else 8,
                            sizes=[10, 14], violation_frac=0.3,
                            tenant_prefix="wave2")
        admitted2 = submit_cases(d1.url, wave2)
        report["wave2_admitted"] = admitted2
        t_kill = time.monotonic()
        d1.sigkill()
        report["killed_pid"] = d1.proc.pid
    except Exception as e:                              # noqa: BLE001
        violate(f"phase 1 crashed: {type(e).__name__}: {e}")
        try:
            d1.sigkill()
        except Exception:                               # noqa: BLE001
            pass
        return report

    # ---- phase 3: restart (no faults), journal replay recovers ----
    d2 = DaemonProc(root, faults_env="", log_path=log_path)
    try:
        if not _wait_ready(d2.url):
            violate("daemon 2 never became ready after restart")
            return report
        # a duplicate POST with a wave-2 idempotency key must dedup to
        # the ORIGINAL id (the index survived the restart via the WAL)
        dup_target = next((c for c in wave2 if c["id"]), None)
        if dup_target is not None:
            code, resp = _post(d2.url, dup_target["payload"])
            if code != 202 or resp.get("id") != dup_target["id"] \
                    or not resp.get("deduped"):
                violate(f"idempotent re-POST did not dedup to the "
                        f"original id: {code} {resp}")
            report["dedup_across_restart"] = resp
        first_done = poll_terminal(d2.url, wave2, timeout=600)
        if first_done is not None:
            report["recovery_to_first_verdict_s"] = round(
                first_done - t_kill, 3)

        # ---- the session rode the SIGKILL: same id, journaled
        # appends replayed (no lost acks), frontier re-derived;
        # post-kill appends continue the stream and close must equal
        # the standalone facade on the full concatenated history ----
        if sess_id is not None:
            code, st = _get(d2.url, f"/session/{sess_id}")
            if code != 200 or st.get("status") != "open":
                violate(f"session {sess_id} lost across restart: "
                        f"{code} {st}")
            elif int(st.get("seq", -1)) != n_pre:
                violate(f"session replay lost appends: seq "
                        f"{st.get('seq')} != {n_pre}")
            else:
                report["session_replayed_appends"] = \
                    st.get("replayed-appends")
                # a RETRIED pre-kill append (its response was lost to
                # the crash, says the client) must dedup, not
                # double-advance the frontier
                code, r = _lg._post_json(
                    d2.url, f"/session/{sess_id}/append",
                    {"history": [op.to_dict()
                                 for op in sess_blocks[n_pre - 1]],
                     "seq": n_pre})
                if code != 200 or not r.get("deduped"):
                    violate(f"retried session append did not dedup: "
                            f"{code} {r}")
                for seq in range(n_pre + 1, len(sess_blocks) + 1):
                    code, r = _lg._post_json(
                        d2.url, f"/session/{sess_id}/append",
                        {"history": [op.to_dict() for op in
                                     sess_blocks[seq - 1]],
                         "seq": seq, "wait-s": 120})
                    if code != 200 \
                            or r.get("valid-so-far") is not True:
                        violate(f"post-kill session append {seq} "
                                f"bad: {code} {r}")
                code, r = _lg._post_json(
                    d2.url, f"/session/{sess_id}/close", {})
                sres = (r.get("result") or {}) if code == 200 else {}
                report["session_close"] = {
                    "valid": sres.get("valid"),
                    "engine": sres.get("engine"),
                    "incremental": sres.get("incremental")}
                if code != 200 or sres.get("valid") is not True:
                    violate(f"session close verdict wrong: "
                            f"{code} {r}")
                else:
                    from jepsen_tpu import history as _h
                    from jepsen_tpu import models as _models
                    from jepsen_tpu.checkers import facade as _facade
                    stand = _facade.auto_check_packed(
                        _models.cas_register(), _h.pack(sess_hist),
                        {})
                    if stand["valid"] is not sres.get("valid"):
                        violate("session close diverges from the "
                                "standalone facade")

        # invariant 1: every 202 reached a terminal state
        for c in wave1 + wave2 + ([poison_case] if poison_case
                                  else []):
            if c["id"] and (c["final"] is None
                            or c["final"].get("status")
                            not in _TERMINAL):
                violate(f"request {c['id']} never reached a terminal "
                        f"state: {c['final']}")

        # invariant 2: verdicts equal ground truth AND the standalone
        # facade differential (bit-identical valid + witness op)
        from jepsen_tpu import history as h
        from jepsen_tpu import models
        from jepsen_tpu.checkers import facade
        mismatches = 0
        for c in wave1 + wave2:
            st = c["final"] or {}
            if st.get("status") != "done":
                continue
            valid = (st.get("result") or {}).get("valid")
            if valid is not c["expect"]:
                mismatches += 1
                violate(f"verdict mismatch for {c['id']}: got "
                        f"{valid!r}, ground truth {c['expect']!r}")
                continue
            stand = facade.auto_check_packed(
                models.cas_register(), h.pack(c["ops"]), {})
            if stand["valid"] is not valid:
                mismatches += 1
                violate(f"daemon verdict diverges from standalone "
                        f"facade for {c['id']}")
            elif valid is False and \
                    st["result"].get("op") != stand.get("op"):
                mismatches += 1
                violate(f"witness op diverges for {c['id']}: "
                        f"{st['result'].get('op')} vs "
                        f"{stand.get('op')}")
        report["verdict_mismatches"] = mismatches
        report["checked_done"] = sum(
            1 for c in wave1 + wave2
            if (c["final"] or {}).get("status") == "done")

        # invariant 5: journal fully drained + non-degraded health
        code, hz = _get(d2.url, "/healthz")
        report["final_healthz"] = hz
        if code != 200 or hz.get("ok") is not True:
            violate(f"final /healthz not ok: {code} {hz}")
        if hz.get("degraded") is not False:
            violate(f"daemon still degraded after recovery: "
                    f"{hz.get('breaker')}")
        if (hz.get("journal") or {}).get("pending") != 0:
            violate(f"journal not drained: {hz.get('journal')}")
        jdir = os.path.join(root, "serve", "journal")
        pending_files = [f for f in os.listdir(jdir)
                         if f.endswith(".req.json")
                         and not os.path.exists(os.path.join(
                             jdir, f[:-len(".req.json")]
                             + ".done.json"))]
        if pending_files:
            violate(f"pending journal entries on disk: "
                    f"{pending_files}")
        rc = d2.sigterm()
        if rc != 0:
            violate(f"daemon 2 SIGTERM exit code {rc}")
    except Exception as e:                              # noqa: BLE001
        violate(f"phase 3 crashed: {type(e).__name__}: {e}")
        try:
            d2.sigkill()
        except Exception:                               # noqa: BLE001
            pass

    report["ok"] = not report["violations"]
    if not keep_store and report["ok"] and not opts.get("store_root"):
        shutil.rmtree(root, ignore_errors=True)
        report["store_root"] = None
    return report


# -- the fleet harness ---------------------------------------------------

def run_fleet(opts: Dict[str, Any]) -> Dict[str, Any]:
    """N-replica fleet over ONE store root: SIGKILL a replica
    mid-load and assert its leased work drains through the survivors.

    Gates (the fleet analogues of the single-daemon invariants):

    1. Every 202 — including those leased to the victim at the kill —
       reaches a terminal state via a SURVIVOR, with verdicts equal
       to ground truth and the standalone facade.
    2. Lease failover is visible: any entry the victim held at the
       kill shows up in survivor counters as expired+stolen.
    3. No double-dispatch: every terminal response is STABLE and
       bit-identical from every surviving replica (a second dispatch
       with a divergent outcome would flip one of them).
    4. Cross-replica idempotency: a duplicate POST to a *different*
       replica dedups to the original id through the shared journal.
    5. A streaming session pinned to the victim is adopted by a
       survivor after lease expiry and closes with the exact
       standalone-facade verdict.
    """
    quick = bool(opts.get("quick"))
    seed = int(opts.get("seed", 7))
    n_replicas = max(2, int(opts.get("replicas", 2)))
    keep_store = bool(opts.get("keep_store"))
    lease_ttl = 1.5
    root = opts.get("store_root") or tempfile.mkdtemp(
        prefix="jepsen-fleet-")
    os.makedirs(root, exist_ok=True)
    report: Dict[str, Any] = {"store_root": root, "seed": seed,
                              "quick": quick, "replicas": n_replicas,
                              "lease_ttl_s": lease_ttl,
                              "violations": []}

    def violate(msg: str) -> None:
        report["violations"].append(msg)

    procs: List[DaemonProc] = []
    for i in range(n_replicas):
        procs.append(DaemonProc(
            root, faults_env="",
            log_path=os.path.join(root, f"fleet-r{i}.log"),
            extra_args=["--replica-id", f"r{i}",
                        "--lease-ttl", str(lease_ttl),
                        "--lanes", "2"]))
    urls = [p.url for p in procs]
    report["urls"] = urls
    victim, survivors = procs[0], procs[1:]

    def _stats(url: str) -> Dict[str, float]:
        code, st = _get(url, "/stats")
        return st.get("counters", {}) if code == 200 else {}

    try:
        for p in procs:
            if not _wait_ready(p.url):
                violate(f"replica {p.url} never became ready")
                return report

        # ---- wave 1: round-robin across every replica ----
        wave1 = build_cases(seed=seed, n=8 if quick else 16,
                            sizes=[8, 12] if quick else [8, 12, 16],
                            violation_frac=0.3, tenant_prefix="fleet")
        for i, c in enumerate(wave1):
            submit_cases(urls[i % len(urls)], [c])

        # gate 4: duplicate POST to a DIFFERENT replica than the one
        # that admitted it must dedup to the original id (the shared
        # journal index is the source of truth, not process memory)
        dup = next((c for c in wave1 if c["id"]), None)
        if dup is not None:
            code, resp = _post(urls[1], dup["payload"])
            if code not in (200, 202) or resp.get("id") != dup["id"] \
                    or not resp.get("deduped"):
                violate(f"cross-replica re-POST did not dedup to the "
                        f"original id: {code} {resp}")
            report["cross_replica_dedup"] = resp

        # every replica answers GET /check/<id> for every id (done
        # markers + journal are shared) — poll wave 1 via replica 1
        poll_terminal(urls[1], wave1, timeout=600)

        # ---- streaming session PINNED to the victim ----
        from jepsen_tpu import fixtures as _fx
        sess_hist = _fx.gen_history("cas", n_ops=36 if quick else 72,
                                    processes=3, seed=seed + 2000)
        blk = 12
        sess_blocks = [sess_hist[i:i + blk]
                       for i in range(0, len(sess_hist), blk)]
        sess_id = None
        code, resp = _lg._post_json(victim.url, "/session",
                                    {"model": "cas-register",
                                     "tenant": "fleet-sess"})
        if code != 201:
            violate(f"session open on victim failed: {code} {resp}")
        else:
            sess_id = resp["session"]
            if resp.get("pinned-to") != "r0":
                violate(f"session not pinned to its opener: {resp}")
            code, r = _lg._post_json(
                victim.url, f"/session/{sess_id}/append",
                {"history": [op.to_dict() for op in sess_blocks[0]],
                 "seq": 1, "wait-s": 120})
            if code != 200 or r.get("valid-so-far") is not True:
                violate(f"pre-kill session append bad: {code} {r}")

        # ---- kill wave: submitted to the VICTIM, then SIGKILL
        # before it can finish — this is the leased work that must
        # drain through the survivors ----
        kill_wave = build_cases(seed=seed + 1000,
                                n=6 if quick else 10,
                                sizes=[12, 16], violation_frac=0.3,
                                tenant_prefix="kill")
        submit_cases(victim.url, kill_wave)
        t_kill = time.monotonic()
        victim.sigkill()
        report["killed"] = "r0"

        # the victim is dead, so its on-disk lease state is frozen
        # until the TTL: count the entries it still held
        jdir = os.path.join(root, "serve", "journal")
        victim_pending = []
        for f in os.listdir(jdir):
            if not f.endswith(".lease.json"):
                continue
            eid = f[:-len(".lease.json")]
            if os.path.exists(os.path.join(jdir,
                                           eid + ".done.json")):
                continue
            try:
                with open(os.path.join(jdir, f)) as fh:
                    holder = json.load(fh).get("replica")
            except (OSError, ValueError):
                continue
            if holder == "r0":
                victim_pending.append(eid)
        report["victim_pending_at_kill"] = len(victim_pending)

        # ---- gate 1: everything drains through the survivors ----
        first_done = poll_terminal(urls[1], kill_wave, timeout=600)
        if first_done is not None:
            report["failover_to_first_verdict_s"] = round(
                first_done - t_kill, 3)
        for c in wave1 + kill_wave:
            if c["id"] and (c["final"] is None
                            or c["final"].get("status")
                            not in _TERMINAL):
                violate(f"request {c['id']} never drained through "
                        f"the survivors: {c['final']}")

        # gate 2: if the victim held leases at the kill, the
        # survivors must have visibly expired + stolen them
        if victim_pending:
            stolen = sum(_stats(u).get("serve.lease.stolen", 0)
                         for u in urls[1:])
            if stolen < 1:
                violate(f"victim held {len(victim_pending)} leases "
                        f"but no survivor recorded a steal")
            report["leases_stolen"] = stolen

        # verdicts: ground truth + standalone facade differential
        from jepsen_tpu import history as h
        from jepsen_tpu import models
        from jepsen_tpu.checkers import facade
        mismatches = 0
        for c in wave1 + kill_wave:
            st = c["final"] or {}
            if st.get("status") != "done":
                continue
            valid = (st.get("result") or {}).get("valid")
            if valid is not c["expect"]:
                mismatches += 1
                violate(f"verdict mismatch for {c['id']}: got "
                        f"{valid!r}, ground truth {c['expect']!r}")
                continue
            stand = facade.auto_check_packed(
                models.cas_register(), h.pack(c["ops"]), {})
            if stand["valid"] is not valid:
                mismatches += 1
                violate(f"fleet verdict diverges from standalone "
                        f"facade for {c['id']}")
            elif valid is False and \
                    st["result"].get("op") != stand.get("op"):
                mismatches += 1
                violate(f"witness op diverges for {c['id']}")
        report["verdict_mismatches"] = mismatches
        report["checked_done"] = sum(
            1 for c in wave1 + kill_wave
            if (c["final"] or {}).get("status") == "done")

        # gate 3: terminal responses are stable and identical from
        # EVERY surviving replica (double-dispatch with a divergent
        # outcome would flip one of these)
        for c in wave1 + kill_wave:
            if not c["id"] or (c["final"] or {}).get("status") \
                    not in _TERMINAL:
                continue
            for u in urls[1:]:
                code, st = _get(u, f"/check/{c['id']}")
                if st.get("status") != c["final"].get("status") or \
                        (st.get("result") or {}).get("valid") != \
                        (c["final"].get("result") or {}).get("valid"):
                    violate(f"terminal response for {c['id']} not "
                            f"identical across replicas: "
                            f"{st} vs {c['final']}")

        # ---- gate 5: the victim's session is adopted by a survivor
        # after lease expiry and closes with the facade verdict ----
        if sess_id is not None:
            surv = urls[1]
            adopted = False
            end = time.monotonic() + max(20.0, 6 * lease_ttl)
            for seq in range(2, len(sess_blocks) + 1):
                while True:
                    code, r = _lg._post_json(
                        surv, f"/session/{sess_id}/append",
                        {"history": [op.to_dict()
                                     for op in sess_blocks[seq - 1]],
                         "seq": seq, "wait-s": 120})
                    if code == 409 and r.get("cause") == \
                            "session-pinned":
                        # still leased to the dead victim — wait out
                        # the TTL, the survivor will adopt
                        if time.monotonic() > end:
                            violate(f"session never adopted: {r}")
                            break
                        time.sleep(0.3)
                        continue
                    break
                if code != 200 or r.get("valid-so-far") is not True:
                    violate(f"post-kill session append {seq} bad: "
                            f"{code} {r}")
                    break
                adopted = True
            if adopted:
                # adoption is counted on whichever path got there
                # first: the append handler (serve.session.adopted)
                # or the background fleet scan (serve.session.replayed
                # after a lease steal) — either way the takeover must
                # be in the ledger
                scount = _stats(surv)
                if scount.get("serve.session.adopted", 0) < 1 and \
                        scount.get("serve.session.replayed", 0) < 1:
                    violate("session continued on a survivor but no "
                            "adoption/replay was ever counted")
                code, r = _lg._post_json(
                    surv, f"/session/{sess_id}/close", {})
                sres = (r.get("result") or {}) if code == 200 else {}
                report["session_close"] = {
                    "valid": sres.get("valid"),
                    "incremental": sres.get("incremental")}
                stand = facade.auto_check_packed(
                    models.cas_register(), h.pack(sess_hist), {})
                if code != 200 or \
                        sres.get("valid") is not stand["valid"]:
                    violate(f"adopted session close diverges from "
                            f"the standalone facade: {code} {r}")

        # survivors end healthy, drained, and exit clean
        for i, p in enumerate(survivors, start=1):
            code, hz = _get(p.url, "/healthz")
            if code != 200 or hz.get("ok") is not True:
                violate(f"replica r{i} final /healthz not ok: "
                        f"{code} {hz}")
            if hz.get("degraded") is not False:
                violate(f"replica r{i} degraded after failover")
        pending_files = [f for f in os.listdir(jdir)
                         if f.endswith(".req.json")
                         and not os.path.exists(os.path.join(
                             jdir, f[:-len(".req.json")]
                             + ".done.json"))]
        if pending_files:
            violate(f"pending journal entries on disk: "
                    f"{pending_files}")
        for i, p in enumerate(survivors, start=1):
            rc = p.sigterm()
            if rc != 0:
                violate(f"replica r{i} SIGTERM exit code {rc}")
    except Exception as e:                              # noqa: BLE001
        violate(f"fleet harness crashed: {type(e).__name__}: {e}")
        for p in procs:
            try:
                if p.proc.poll() is None:
                    p.sigkill()
            except Exception:                           # noqa: BLE001
                pass

    report["ok"] = not report["violations"]
    if not keep_store and report["ok"] and not opts.get("store_root"):
        shutil.rmtree(root, ignore_errors=True)
        report["store_root"] = None
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="self-nemesis chaos harness for the check-serve "
                    "daemon (seeded faults + SIGKILL/restart)")
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke: one dispatch fault + one "
                         "SIGKILL/restart")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--fleet", action="store_true",
                    help="N-replica fleet over one store root: "
                         "SIGKILL one replica mid-load, gate on "
                         "lease failover through the survivors")
    ap.add_argument("--replicas", type=int, default=2,
                    help="fleet size for --fleet (default 2)")
    ap.add_argument("--store-root", default=None,
                    help="use (and keep) this store root instead of "
                         "a temp dir")
    ap.add_argument("--keep-store", action="store_true",
                    help="keep the temp store root for inspection")
    args = ap.parse_args(argv)
    opts = {"quick": args.quick, "seed": args.seed,
            "store_root": args.store_root,
            "keep_store": args.keep_store,
            "replicas": args.replicas}
    report = run_fleet(opts) if args.fleet else run_chaos(opts)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
