"""Full-harness soak: run every suite end-to-end across modes and seeds
and assert the EXPECTED verdict for each (correct modes must pass,
deliberately-buggy modes must be caught). This exercises the whole
stack — generators, worker threads, nemeses, fault injection, clients
(including the etcd HTTP wire path), checkers, store — far longer than
the CI tier does.

Usage: python tools/soak.py [--rounds 3] [--time-limit 2.0] [--seed 0]
Exit 1 on any unexpected verdict. One JSON summary line at the end.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(suite: str, mode: str, seed: int, time_limit: float):
    from jepsen_tpu.suites import (counter, etcd, mutex, queue, redis,
                                   register, set_suite)
    kw = dict(time_limit=time_limit, seed=seed, store=False,
              with_nemesis=True, nemesis_interval=0.3)
    if suite == "register":
        return register.register_test(mode, concurrency=5, **kw)
    if suite == "etcd":
        return etcd.etcd_test(mode, concurrency=5, **kw)
    if suite == "redis":
        return redis.redis_test(mode, concurrency=5, **kw)
    if suite == "mutex":
        return mutex.mutex_test(mode, concurrency=4, **kw)
    if suite == "queue":
        return queue.queue_test(mode, concurrency=4, **kw)
    if suite == "set":
        return set_suite.set_test(mode, concurrency=4, **kw)
    if suite == "counter":
        return counter.counter_test(mode, concurrency=4, **kw)
    raise ValueError(suite)


# (suite, mode, expected top-level valid). Buggy modes rely on nemesis
# timing, so their expectation is "False OR True" only when the fault
# window may not align — the strict ones are the deliberately-seeded
# deterministic configs asserted in tests/; here sloppy modes get
# several rounds so a never-caught bug still fails the soak overall.
CONFIGS = [
    ("register", "linearizable", True),
    ("register", "sloppy", False),
    ("etcd", "linearizable", True),
    ("etcd", "sloppy", False),
    ("redis", "linearizable", True),
    ("redis", "sloppy", False),
    ("mutex", "linearizable", True),
    # lease-based lock + clock-bump nemesis (bump-time analogue): safe
    # clocks keep it linearizable; the skewed node double-grants
    ("mutex", "leases", False),
    ("queue", "safe", True),
    ("queue", "lossy", False),
    ("set", "linearizable", True),
    ("set", "sloppy", False),
    ("counter", "linearizable", True),
    ("counter", "sloppy", False),
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--time-limit", type=float, default=2.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from jepsen_tpu import core, obs

    rng = random.Random(args.seed)
    t0 = time.monotonic()
    runs = 0
    failures = []                       # unexpected verdicts
    caught = {}                         # (suite,mode) -> times invalid seen
    for rnd in range(args.rounds):
        for suite, mode, expect in CONFIGS:
            seed = rng.randrange(1 << 30)
            test = build(suite, mode, seed, args.time_limit)
            try:
                done = core.run(test)
                valid = done["results"].get("valid")
            except Exception as e:                      # noqa: BLE001
                # a crash must not discard the completed rounds or the
                # final summary — record it as an unexpected outcome
                valid = f"crash: {type(e).__name__}: {e}"
            runs += 1
            key = f"{suite}-{mode}"
            if valid is False:
                caught[key] = caught.get(key, 0) + 1
            if expect is True and valid is not True:
                failures.append({"round": rnd, "suite": suite,
                                 "mode": mode, "seed": seed,
                                 "valid": valid})
                print(f"UNEXPECTED {key} seed={seed}: valid={valid}",
                      file=sys.stderr)
    # a buggy mode that was NEVER caught across all rounds is a miss
    for suite, mode, expect in CONFIGS:
        if expect is False and caught.get(f"{suite}-{mode}", 0) == 0:
            failures.append({"suite": suite, "mode": mode,
                             "error": "bug never caught"})
            print(f"NEVER CAUGHT: {suite}-{mode}", file=sys.stderr)
    # cross-run observability: every run's engine selections and every
    # silent-degradation counter, aggregated over the whole soak (each
    # run's own ledger also lands in its results["obs"])
    snap = obs.counters()
    print(json.dumps({
        "runs": runs, "unexpected": len(failures),
        "caught": caught,
        "obs": {k: v for k, v in sorted(snap.items())
                if k.startswith(("engine.", "checker.swallowed.",
                                 "reach.", "lockstep."))},
        "elapsed_s": round(time.monotonic() - t0, 1)}))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
