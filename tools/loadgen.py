"""Open-loop load generator for the checker-as-a-service daemon.

Replays a mixed-geometry history workload (several sizes, several
tenants, a configurable fraction of known-violating histories)
against a running daemon at a target arrival rate and reports
sustained req/s plus p50/p99 verdict latency — split into two
measurement windows so the warm-cache effect is a number, not an
anecdote (window 2 runs entirely on compiled geometries and seeded
memo tables; it should beat window 1).

Open-loop means arrivals are scheduled by the clock, not by
completions: if the daemon falls behind, the queue grows and
backpressure 429s show up in the report instead of the generator
politely slowing down — that is the regime a "millions of users"
front door actually faces.

Usage::

    python tools/loadgen.py --url http://127.0.0.1:8642 [--quick]
    python tools/loadgen.py --self-host --rate 20 --duration 10

Exit status: 0 iff at least one request completed AND every verdict
matched its history's known ground truth AND the latency cross-check
passed (loadgen's client-measured p50/p99 vs the daemon's
histogram-derived quantiles over the /metrics delta — >15%
disagreement past the poll-resolution slack means a clock/stamping
bug). The report also splits queue-wait from service time using the
daemon's stage timestamps, and the final ``/stats`` snapshot rides
along (the CI smoke job asserts zero silent fallbacks from it).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_pool(*, sizes, tenants: int, violation_frac: float,
               model: str = "cas-register", seed: int = 7,
               kinds: Tuple[str, ...] = ("cas",)) -> List[Dict]:
    """Pre-generate the payload pool: one entry per (size, kind)
    pair per tenant slot, each a ready-to-POST body plus its known
    ground-truth verdict."""
    from jepsen_tpu import fixtures

    pool: List[Dict] = []
    i = 0
    for kind in kinds:
        for n_ops in sizes:
            for t in range(tenants):
                i += 1
                hist = fixtures.gen_history(kind, n_ops=n_ops,
                                            processes=3,
                                            seed=seed + i)
                expect = True
                if (i * 1000 % 997) / 997.0 < violation_frac:
                    hist = fixtures.corrupt(hist, seed=seed + i)
                    expect = False
                pool.append({
                    "tenant": f"tenant-{t}",
                    "expect": expect,
                    "ops": len(hist),
                    "body": json.dumps({
                        "model": model,
                        "tenant": f"tenant-{t}",
                        "history": [op.to_dict() for op in hist],
                    }).encode(),
                })
    return pool


def build_txn_pool(*, tenants: int, seed: int = 7,
                   clean_sizes: Tuple[int, ...] = (12, 30)
                   ) -> List[Dict]:
    """``--mixed-consistency`` payload pool: transactional histories
    with KNOWN per-level lattice ground truth, each submitted at one
    requested consistency level (entries round-robin the level set,
    so one coalescer carries mixed-level traffic — same-level
    requests batch together, different-level sets form their own
    groups). Every entry carries the full expected ``holds`` map: the
    exit gate asserts the daemon's per-level verdicts, not just the
    boolean."""
    from jepsen_tpu import fixtures
    from jepsen_tpu.txn import lattice

    all_true = {lvl: True for lvl in lattice.LEVELS}
    all_false = {lvl: False for lvl in lattice.LEVELS}
    skew = {"read-committed": True, "causal": True, "pl-2": True,
            "si": False, "serializable": False}
    fixture_holds = {
        "write-skew": skew,
        "lost-update": all_false,
        "long-fork": dict(skew),
        "session-mr": {"read-committed": True, "causal": True,
                       "pl-2": False, "si": False,
                       "serializable": False},
    }
    variants: List[Tuple[str, List, Dict[str, bool]]] = []
    for i, n in enumerate(clean_sizes):
        variants.append(
            ("clean", fixtures.gen_txn_history(n, seed=seed + i),
             all_true))
    for kind in fixtures.TXN_LATTICE_KINDS:
        variants.append(
            (kind, fixtures.txn_anomaly_block(kind), fixture_holds[kind]))
    pool: List[Dict] = []
    i = 0
    for t in range(tenants):
        for name, hist, holds in variants:
            level = lattice.LEVELS[i % len(lattice.LEVELS)]
            i += 1
            pool.append({
                "tenant": f"tenant-{t}",
                "expect": holds[level],
                "expect_holds": dict(holds),
                "level": level, "kind": name,
                "ops": len(hist),
                "body": json.dumps({
                    "model": "txn-list-append",
                    "tenant": f"tenant-{t}",
                    "options": {"consistency": [level]},
                    "history": [op.to_dict() for op in hist],
                }).encode(),
            })
    return pool


def _post(url: str, body: bytes, path: str = "/check",
          timeout: float = 30.0) -> Tuple[int, Dict]:
    req = urllib.request.Request(
        url + path, data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:                               # noqa: BLE001
            return e.code, {}
    except Exception:                                   # noqa: BLE001
        # URLError / reset / socket timeout: transport failure, not an
        # HTTP status — the caller records it instead of losing the
        # request from the report's accounting
        return -1, {}


def _get(url: str, path: str) -> Tuple[int, Dict]:
    try:
        with urllib.request.urlopen(url + path, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            # error statuses can carry structured bodies (e.g. the
            # 500 of a quarantined request) — keep them
            return e.code, json.loads(e.read())
        except Exception:                               # noqa: BLE001
            return e.code, {}
    except Exception:                                   # noqa: BLE001
        return -1, {}


def _get_text(url: str, path: str) -> Tuple[int, str]:
    try:
        with urllib.request.urlopen(url + path, timeout=30) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, ""
    except Exception:                                   # noqa: BLE001
        return -1, ""


def fetch_hist_buckets(url: str,
                       metric: str = "jepsen_serve_e2e_s"
                       ) -> Optional[List[Tuple[float, float]]]:
    """Scrape /metrics and return the CUMULATIVE ``(le, count)``
    bucket pairs of one histogram (None when the endpoint or the
    series is missing)."""
    from jepsen_tpu import obs

    code, text = _get_text(url, "/metrics")
    if code != 200 or not text:
        return None
    samples = obs.parse_prometheus(text)
    rows = samples.get(metric + "_bucket")
    if not rows:
        return None
    return sorted((float(labels["le"]), v) for labels, v in rows)


def wait_ready(url: str, timeout: float = 30.0) -> bool:
    """Poll /healthz until the daemon answers (the CI smoke job
    starts the daemon in the background and races its jax import)."""
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        try:
            code, _ = _get(url, "/healthz")
            if code == 200:
                return True
        except Exception:                               # noqa: BLE001
            pass
        time.sleep(0.2)
    return False


def _percentile(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    s = sorted(xs)
    i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[i]


def _window_report(records: List[Dict], t_start: float,
                   t_mid: float, t_end: float) -> List[Dict]:
    out = []
    for lo, hi in ((t_start, t_mid), (t_mid, t_end)):
        rs = [r for r in records if lo <= r["t_submit"] < hi]
        done = [r for r in rs if r["status"] == "done"]
        lats = [r["latency_s"] for r in done]
        span = max(1e-9, hi - lo)
        out.append({
            "submitted": len(rs),
            "completed": len(done),
            "rejected_429": sum(1 for r in rs
                                if r["status"] == "rejected"),
            "req_s": round(len(done) / span, 2),
            "p50_s": (round(_percentile(lats, 0.50), 4)
                      if lats else None),
            "p99_s": (round(_percentile(lats, 0.99), 4)
                      if lats else None),
        })
    return out


_POLL_MAX_S = 0.25

# The cross-check is resolution-aware: the daemon's histogram answers
# a quantile only to within its bucket (log-spaced, ratio 10^0.1), so
# the loadgen-side value is compared against the BUCKET INTERVAL
# around the histogram estimate, and the 15% bound applies to the
# distance OUTSIDE that interval. Client-side latency is additionally
# quantized by the poll schedule (a verdict is observed up to
# _POLL_MAX_S after it published) — that much absolute slack rides on
# top. Clock/stamping bugs (unit mixups, monotonic-vs-wall mixes, a
# stage stamped by the wrong thread) disagree by orders of magnitude,
# far past every bound here.
_XCHECK_REL = 0.15
_XCHECK_ABS_S = _POLL_MAX_S + 0.1
_BUCKET_RATIO = 10.0 ** 0.1


def crosscheck_quantiles(lg: Dict[str, Optional[float]],
                         before: Optional[List[Tuple[float, float]]],
                         after: Optional[List[Tuple[float, float]]]
                         ) -> Optional[Dict[str, Any]]:
    """Compare loadgen's own measured p50/p99 against the daemon's
    histogram-derived quantiles over the /metrics DELTA between two
    scrapes (the delta isolates the measured window from warmup
    traffic — cumulative buckets difference bucket-by-bucket).
    Returns the comparison dict (``"ok"`` False on >15% disagreement
    past the poll-resolution slack), or None when either side is
    unavailable."""
    from jepsen_tpu import obs

    if before is None or after is None:
        return None
    b = {le: v for le, v in before}
    delta = [(le, v - b.get(le, 0.0)) for le, v in after]
    out: Dict[str, Any] = {}
    ok = True
    for label, q in (("p50", 0.50), ("p99", 0.99)):
        mine = lg.get(label)
        hist = obs.quantile_from_cumulative(delta, q)
        if mine is None or hist is None:
            out[label] = {"loadgen_s": mine, "hist_s": hist,
                          "ok": None}
            continue
        # distance from loadgen's value to the one-bucket interval
        # around the histogram estimate (inside the interval the two
        # agree as well as the histogram can resolve)
        lo, hi = hist / _BUCKET_RATIO, hist * _BUCKET_RATIO
        diff = max(0.0, lo - mine, mine - hi)
        rel = diff / max(mine, hist, 1e-9)
        this_ok = rel <= _XCHECK_REL or diff <= _XCHECK_ABS_S
        ok = ok and this_ok
        out[label] = {"loadgen_s": round(mine, 4),
                      "hist_s": round(hist, 4),
                      "rel": round(rel, 3), "ok": this_ok}
    out["ok"] = ok
    return out


def _await_ids(url: str, ids: List[str], poll_timeout: float) -> None:
    end = time.monotonic() + poll_timeout
    pending = set(ids)
    poll = 0.02
    while pending and time.monotonic() < end:
        for rid in list(pending):
            code, st = _get(url, f"/check/{rid}")
            if code in (200, 500) and st.get("status") in (
                    "done", "timeout", "cancelled", "quarantined"):
                pending.discard(rid)
        time.sleep(poll)
        poll = min(_POLL_MAX_S, poll * 1.5)


def warmup(url: str, pool: List[Dict], *, burst: int = 8,
           poll_timeout: float = 300.0) -> Dict[str, Any]:
    """Pay the cold-start once, before measurement. Two phases:

    1. one history per distinct size, sequentially — compiles the
       singleton-lane geometries and seeds the memo tables;
    2. concurrent bursts of ``burst`` same-size submissions — forms
       coalesced dispatch groups so the power-of-two group-width
       kernel geometries (the daemon pads widths to those) compile
       now, not inside the measured windows.

    After this the measured run reports steady-state serving — the
    regime a long-lived daemon actually lives in. (Skippable with
    --no-warmup to measure the cold wall itself.)"""
    t0 = time.monotonic()
    n = 0
    seen = set()
    for payload in pool:
        if payload["ops"] in seen:
            continue
        seen.add(payload["ops"])
        code, resp = _post(url, payload["body"])
        if code == 202:
            _await_ids(url, [resp["id"]], poll_timeout)
            n += 1
    by_size: Dict[int, List[Dict]] = {}
    for p in pool:
        by_size.setdefault(p["ops"], []).append(p)
    for size_pool in by_size.values():
        ids = []
        for i in range(burst):
            code, resp = _post(url, size_pool[i % len(size_pool)]
                               ["body"])
            if code == 202:
                ids.append(resp["id"])
        _await_ids(url, ids, poll_timeout)
        n += len(ids)
    return {"requests": n, "wall_s": round(time.monotonic() - t0, 3)}


def _flag_saturation(report: Dict[str, Any], rate: float) -> None:
    """The throughput-regression tripwire (satellite of the pipelined
    dispatch work): a daemon sustaining under 90% of the offered rate
    while the queue-overload waiver stayed EMPTY — no 429s, no
    backlog-regime p99 waiver — is quietly shedding throughput (the
    r08 surface: sustained 13.9 of 20 offered with every gate green).
    Sets ``report["saturated"]`` loudly instead of leaving the ratio
    buried in the JSON."""
    sus = report.get("sustained_req_s")
    if sus is None or not rate:
        return
    waived = ((report.get("latency_crosscheck") or {})
              .get("p99_gate") == "waived-queue-overloaded")
    report["saturated"] = bool(
        sus / float(rate) < 0.9
        and not waived
        and not report.get("rejected_429", 0))


def find_capacity(url: str, pool: List[Dict], *, quick: bool = False,
                  start_rate: float = 8.0, max_rate: float = 512.0,
                  iters: int = 4,
                  urls: Optional[List[str]] = None) -> Dict[str, Any]:
    """Binary-search the offered rate to the daemon's max sustained
    req/s — the number the pipelined dispatch must actually move.
    Doubling phase finds the first UNSUSTAINED rate (sustained below
    90% of offered, or any 429/timeout), then bisection tightens the
    bracket. Probes are short open-loop bursts over the same payload
    pool as the fixed-rate run; every probe is recorded so a noisy
    bracket is visible in the artifact."""
    dur = 3.0 if quick else 6.0
    probes: List[Dict[str, Any]] = []

    def _probe(r: float) -> Tuple[bool, float]:
        rep = run_load(url, rate=r, duration=dur, pool=pool,
                       chaos_tolerant=False, urls=urls)
        rep.pop("_admit_lats", None)
        sus = float(rep.get("sustained_req_s") or 0.0)
        ok = (sus >= 0.9 * r
              and not rep.get("rejected_429", 0)
              and not rep.get("timeouts", 0))
        probes.append({"rate": round(r, 2),
                       "sustained_req_s": round(sus, 2), "ok": ok})
        return ok, sus

    lo, lo_sus = 0.0, 0.0
    r = max(1.0, float(start_rate))
    hi = None
    while hi is None and r <= max_rate:
        ok, sus = _probe(r)
        if ok:
            lo, lo_sus = r, sus
            r *= 2.0
        else:
            hi = r
    if hi is None:
        hi = r                  # sustained everything up to max_rate
    for _ in range(max(0, int(iters))):
        if hi - lo <= max(0.5, 0.05 * hi):
            break
        mid = (lo + hi) / 2.0
        ok, sus = _probe(mid)
        if ok:
            lo, lo_sus = mid, sus
        else:
            hi = mid
    return {"capacity_req_s": round(lo_sus or lo, 2),
            "highest_sustained_rate": round(lo, 2),
            "first_unsustained_rate": round(hi, 2),
            "probes": probes}


def run_load(url: str, *, rate: float, duration: float,
             pool: List[Dict], poll_s: float = 0.01,
             poll_timeout: float = 120.0,
             chaos_tolerant: bool = False,
             urls: Optional[List[str]] = None) -> Dict[str, Any]:
    """Drive the open-loop schedule; returns the report dict.

    ``urls`` (fleet mode): submissions round-robin client-side over
    the replica list (each request polls the replica it was admitted
    by), and the report gains ``per_replica`` submitted/completed/
    req_s splits beside the merged totals.

    ``chaos_tolerant`` (the chaos harness's mode): a connection
    refusal during a scripted daemon kill/restart is expected, not a
    failure — POSTs retry until the daemon returns, refusals are
    recorded as ``error-restart`` (distinct from ``error-net``) only
    when the daemon never comes back, pollers keep polling across the
    gap, and the report carries ``recovery``: the time from the first
    refusal to the first verdict observed after it
    (recovery-time-to-first-verdict)."""
    targets = list(urls) if urls else [url]
    records: List[Dict] = []
    rec_lock = threading.Lock()
    threads: List[threading.Thread] = []
    # restart-recovery bookkeeping (chaos-tolerant mode): first
    # connection refusal seen, and the first verdict after it
    chaos = {"first_refusal": None, "first_verdict_after": None,
             "refusals": 0}
    chaos_lock = threading.Lock()

    def _saw_refusal() -> None:
        with chaos_lock:
            chaos["refusals"] += 1
            if chaos["first_refusal"] is None:
                chaos["first_refusal"] = time.monotonic()

    def _saw_verdict() -> None:
        with chaos_lock:
            if chaos["first_refusal"] is not None \
                    and chaos["first_verdict_after"] is None:
                chaos["first_verdict_after"] = time.monotonic()

    def one(payload: Dict, t_sched: float, url: str) -> None:
        rec = {"tenant": payload["tenant"], "ops": payload["ops"],
               "expect": payload["expect"], "t_submit": t_sched,
               "status": "lost", "latency_s": None, "match": None,
               "replica": url}
        if payload.get("level"):
            rec["level"] = payload["level"]
            rec["kind"] = payload.get("kind")
        t0 = time.monotonic()
        code, resp = _post(url, payload["body"])
        if chaos_tolerant and code == -1:
            # the daemon is (presumably) mid-restart: keep trying
            # until it answers or the poll budget runs out
            _saw_refusal()
            end_post = time.monotonic() + poll_timeout
            while code == -1 and time.monotonic() < end_post:
                time.sleep(0.25)
                code, resp = _post(url, payload["body"])
            if code == -1:
                rec["status"] = "error-restart"
        if code == 429:
            rec["status"] = "rejected"
        elif code == -1:
            rec["status"] = ("error-restart" if chaos_tolerant
                             else "error-net")
        elif code != 202:
            rec["status"] = f"error-{code}"
        else:
            rid = resp["id"]
            # admission anchor: the daemon's e2e histogram starts at
            # ADMIT, while t0 includes submission-side blocking (HTTP
            # worker scheduling, retried POSTs) a saturated daemon
            # never sees — the crosscheck compares like with like
            # from this stamp (the open-loop client latency keeps t0)
            t_admit = time.monotonic()
            end = time.monotonic() + poll_timeout
            # exponential backoff to _POLL_MAX_S: hundreds of
            # in-flight pollers at a fixed 10 ms would out-traffic
            # the load they measure
            poll = poll_s
            while time.monotonic() < end:
                code, st = _get(url, f"/check/{rid}")
                if code == -1 and chaos_tolerant:
                    # daemon gap mid-poll: note it, keep polling —
                    # the journal replay owes us this verdict under
                    # the same id
                    _saw_refusal()
                if code in (200, 500) and st.get("status") in (
                        "done", "timeout", "cancelled",
                        "quarantined"):
                    rec["status"] = st["status"]
                    rec["latency_s"] = time.monotonic() - t0
                    rec["latency_admit_s"] = \
                        time.monotonic() - t_admit
                    valid = (st.get("result") or {}).get("valid")
                    rec["match"] = (valid == payload["expect"]
                                    if st["status"] == "done"
                                    else None)
                    if (rec["match"] and
                            payload.get("expect_holds") is not None):
                        # mixed-consistency pool: the boolean is not
                        # enough — the per-level holds map the daemon
                        # computed at the requested level must match
                        # the fixture's ground truth at that level
                        holds = (st.get("result") or {}).get(
                            "holds") or {}
                        want = {lvl: payload["expect_holds"][lvl]
                                for lvl in
                                (st.get("result") or {}).get(
                                    "consistency", [])}
                        rec["match"] = (
                            want != {} and
                            all(holds.get(lvl) == v
                                for lvl, v in want.items()))
                    if st["status"] == "done":
                        _saw_verdict()
                    # the daemon's stamped stage split (queue wait vs
                    # service) — reported beside the client-side wall
                    rec["queue_wait_s"] = st.get("queue-wait-s")
                    rec["service_s"] = st.get("service-s")
                    break
                time.sleep(poll)
                poll = min(_POLL_MAX_S, poll * 1.5)
        with rec_lock:
            records.append(rec)

    t_start = time.monotonic()
    t_end = t_start + duration
    i = 0
    while True:
        t_sched = t_start + i / rate
        if t_sched >= t_end:
            break
        now = time.monotonic()
        if t_sched > now:
            time.sleep(t_sched - now)
        payload = pool[i % len(pool)]
        th = threading.Thread(
            target=one,
            args=(payload, t_sched, targets[i % len(targets)]),
            daemon=True)
        th.start()
        threads.append(th)
        i += 1
    for th in threads:
        th.join(poll_timeout + 30)
    t_mid = t_start + duration / 2.0
    done = [r for r in records if r["status"] == "done"]
    mismatches = [r for r in records if r["match"] is False]
    wall = max(1e-9, time.monotonic() - t_start)
    report: Dict[str, Any] = {
        "target_rate": rate, "duration_s": duration,
        "submitted": len(records),
        "completed": len(done),
        "rejected_429": sum(1 for r in records
                            if r["status"] == "rejected"),
        "timeouts": sum(1 for r in records
                        if r["status"] == "timeout"),
        "verdict_mismatches": len(mismatches),
        "sustained_req_s": round(len(done) / wall, 2),
        **({"per_level": {
            lvl: {"completed": sum(1 for r in done
                                   if r.get("level") == lvl),
                  "mismatches": sum(1 for r in mismatches
                                    if r.get("level") == lvl)}
            for lvl in sorted({r["level"] for r in records
                               if r.get("level")})}}
           if any(r.get("level") for r in records) else {}),
        "p50_s": _percentile([r["latency_s"] for r in done], 0.50),
        "p99_s": _percentile([r["latency_s"] for r in done], 0.99),
        # admission-anchored quantiles: the window the daemon's e2e
        # histogram actually measures (202 -> terminal) — the
        # latency_crosscheck compares THESE against /metrics
        "p50_admit_s": _percentile(
            [r.get("latency_admit_s") for r in done
             if r.get("latency_admit_s") is not None], 0.50),
        "p99_admit_s": _percentile(
            [r.get("latency_admit_s") for r in done
             if r.get("latency_admit_s") is not None], 0.99),
        # raw admit-anchored samples, kept so run_loadgen can MERGE
        # them with the session-stream samples before the histogram
        # crosscheck (the daemon's e2e histogram covers every
        # completed request — mixed-traffic runs must compare like
        # against like); stripped from the report before return
        "_admit_lats": sorted(
            r["latency_admit_s"] for r in done
            if r.get("latency_admit_s") is not None),
        "windows": _window_report(records, t_start, t_mid,
                                  time.monotonic()),
        # queue-wait vs service-time split from the daemon's stage
        # timestamps (GET /check/<id> waterfall fields)
        "stage_split": {
            kind: {
                "p50_s": _percentile(vals, 0.50),
                "p99_s": _percentile(vals, 0.99),
                "mean_s": (round(sum(vals) / len(vals), 4)
                           if vals else None),
            }
            for kind, vals in (
                ("queue_wait",
                 [r["queue_wait_s"] for r in done
                  if isinstance(r.get("queue_wait_s"),
                                (int, float))]),
                ("service",
                 [r["service_s"] for r in done
                  if isinstance(r.get("service_s"),
                                (int, float))]))},
    }
    if len(targets) > 1:
        report["per_replica"] = {
            u: {"submitted": len(sub),
                "completed": len(dn),
                "req_s": round(len(dn) / wall, 2)}
            for u in targets
            for sub in [[r for r in records
                         if r.get("replica") == u]]
            for dn in [[r for r in sub if r["status"] == "done"]]}
    with chaos_lock:
        if chaos["refusals"]:
            rec_s = None
            if chaos["first_verdict_after"] is not None:
                rec_s = round(chaos["first_verdict_after"]
                              - chaos["first_refusal"], 3)
            report["recovery"] = {
                "refusals": chaos["refusals"],
                "restart_errors": sum(
                    1 for r in records
                    if r["status"] == "error-restart"),
                "recovery_to_first_verdict_s": rec_s,
            }
    code, stats = _get(targets[0], "/stats")
    if code == 200:
        report["stats"] = stats
        counters = stats.get("counters", {})
        report["fallbacks"] = {
            k: v for k, v in counters.items()
            if k.startswith(("engine.fallback.",
                             "checker.swallowed."))}
    if len(targets) > 1:
        report["replica_stats"] = {}
        for u in targets[1:]:
            code, st = _get(u, "/stats")
            if code == 200:
                report["replica_stats"][u] = st
    return report


def build_session_plans(*, n_sessions: int, ops_per_session: int,
                        appends: int, violation_frac: float,
                        seed: int = 7,
                        tenants: Optional[int] = None) -> List[Dict]:
    """Session traffic plans: each a known-ground-truth history split
    into append blocks (violating sessions get a corrupted stream, so
    the incremental verdict has something to catch). ``tenants``
    spreads sessions over that many tenant names (default 2 — the
    historical mixed-traffic shape; thousand-session mux runs need a
    spread wide enough to clear the per-tenant open-session cap)."""
    from jepsen_tpu import fixtures

    n_tenants = max(1, int(tenants or 2))
    plans = []
    for i in range(n_sessions):
        hist = fixtures.gen_history("cas", n_ops=ops_per_session,
                                    processes=3, seed=seed + 100 + i)
        expect = True
        if (i * 997 % 101) / 101.0 < violation_frac:
            hist = fixtures.corrupt(hist, seed=seed + i)
            expect = False
        step = max(1, len(hist) // appends)
        blocks = [hist[j:j + step]
                  for j in range(0, len(hist), step)]
        plans.append({"tenant": f"sess-tenant-{i % n_tenants}",
                      "expect": expect,
                      "blocks": [[op.to_dict() for op in b]
                                 for b in blocks]})
    return plans


def fetch_counter(url: str, name: str) -> Optional[float]:
    """One counter's current value off /metrics (raw jepsen name,
    e.g. ``serve.session.appends``); None when the endpoint or the
    series is missing."""
    from jepsen_tpu import obs

    code, text = _get_text(url, "/metrics")
    if code != 200 or not text:
        return None
    sane = "jepsen_" + "".join(
        c if (c.isalnum() or c == "_") else "_" for c in name)
    rows = obs.parse_prometheus(text).get(sane)
    return rows[0][1] if rows else None


_MUX_COUNTERS = ("serve.session.appends",
                 "serve.session.mega.groups",
                 "serve.session.mega.lanes")


def _mux_efficiency(before: Dict[str, Optional[float]],
                    after: Dict[str, Optional[float]]
                    ) -> Optional[Dict[str, Any]]:
    """Appends-per-dispatch over the measured window: a mega wave of
    L lanes replaces L solo dispatches with ONE kernel launch, so
    dispatches = appends - lanes + groups. 1.0 = no multiplexing."""
    deltas = {}
    for k in _MUX_COUNTERS:
        b, a = before.get(k), after.get(k)
        deltas[k] = (a or 0.0) - (b or 0.0) if a is not None else 0.0
    appends = deltas["serve.session.appends"]
    if appends <= 0:
        return None
    dispatches = max(
        1.0, appends - deltas["serve.session.mega.lanes"]
        + deltas["serve.session.mega.groups"])
    return {"appends": int(appends),
            "dispatches": int(dispatches),
            "mega_groups": int(deltas["serve.session.mega.groups"]),
            "mega_lanes": int(deltas["serve.session.mega.lanes"]),
            "mux_efficiency": round(appends / dispatches, 2)}


def run_session_traffic(url: str, plans: List[Dict], *,
                        cadence_s: float = 0.15,
                        wait_s: float = 60.0,
                        workers: Optional[int] = None,
                        poll_s: float = 0.05) -> Dict[str, Any]:
    """Drive long-lived sessions and gate their verdicts against
    ground truth: a valid stream must never be flagged, a violating
    stream must be flagged by close at the latest (earlier =
    streaming win, counted). Reports the per-append-latency
    distribution — the append-to-verdict number the session protocol
    exists for — plus the window's ``mux`` sub-object
    (appends-per-dispatch off the daemon's mega counters).

    The driver is a WORKER POOL over an event heap, not a thread per
    session: each session is a tiny state machine (open -> append ->
    poll verdict -> ... -> close) scheduled by due time, so five
    thousand live streams ride a few dozen threads. Small runs
    (sessions <= workers) post appends synchronously; large runs
    post with ``wait-s: 0`` and poll the verdict out — the async
    shape that lets thousands of appends sit queued at once, which
    is exactly what the daemon's mega-batch dispatch multiplexes
    into single kernel launches."""
    import heapq

    nworkers = int(workers or min(64, max(4, len(plans))))
    sync_wait = wait_s if len(plans) <= nworkers else 0.0
    results: List[Dict] = []
    lock = threading.Lock()
    cond = threading.Condition(lock)
    heap: List[Any] = []        # (due, tiebreak, idx)
    tick = [0]
    done = [0]

    class _S:                   # per-session driver state
        __slots__ = ("plan", "rec", "sid", "seq", "retried",
                     "pending", "t0", "deadline")

        def __init__(self, plan: Dict) -> None:
            self.plan = plan
            self.rec: Dict[str, Any] = {
                "expect": plan["expect"], "appends": 0,
                "latencies": [], "flagged_at": None, "final": None,
                "errors": 0}
            self.sid: Optional[str] = None
            self.seq = 0                # last submitted append seq
            self.retried = False
            self.pending: Optional[str] = None   # polled request id
            self.t0 = 0.0
            self.deadline = 0.0

    states = [_S(p) for p in plans]

    def _push(idx: int, due: float) -> None:
        with cond:
            tick[0] += 1
            heapq.heappush(heap, (due, tick[0], idx))
            cond.notify()

    def _settle(s: _S, idx: int, r: Dict) -> None:
        """One append verdict is in: record it and schedule the next
        block (or the close) a cadence later."""
        s.rec["appends"] += 1
        s.rec["latencies"].append(time.monotonic() - s.t0)
        if s.rec["flagged_at"] is None \
                and r.get("valid-so-far") is False:
            s.rec["flagged_at"] = s.seq
        s.pending = None
        s.retried = False
        _push(idx, time.monotonic() + cadence_s)

    def _fail_block(s: _S, idx: int) -> None:
        """An append gave out (transport / timeout / backpressure
        past the retry): count it and close the session out — its
        later blocks would only cascade seq-gap 409s."""
        s.rec["errors"] += 1
        s.seq = len(s.plan["blocks"])       # jump to the close step
        s.pending = None
        _push(idx, time.monotonic())

    def _step(idx: int) -> None:
        s = states[idx]
        if s.sid is None:
            code, resp = _post_json(url, "/session",
                                    {"model": "cas-register",
                                     "tenant": s.plan["tenant"]})
            if code != 201:
                s.rec["errors"] += 1
                s.rec["final"] = f"open-error-{code}"
                with cond:
                    results.append(s.rec)
                    done[0] += 1
                    cond.notify_all()
                return
            s.sid = resp["session"]
            s.rec["session"] = s.sid
            _push(idx, time.monotonic())
            return
        if s.pending is not None:
            # poll a 202'd append's verdict out of GET /check/<id>
            code, st = _get(url, f"/check/{s.pending}")
            if code == 200 and st.get("status") == "done" \
                    and st.get("result"):
                _settle(s, idx, st["result"])
            elif time.monotonic() > s.deadline:
                _fail_block(s, idx)
            else:
                _push(idx, time.monotonic() + poll_s)
            return
        if s.seq >= len(s.plan["blocks"]):
            t0c = time.monotonic()
            code, r = _post_json(url, f"/session/{s.sid}/close", {})
            if code == 200:
                s.rec["final"] = (r.get("result") or {}).get("valid")
                # the close dispatches the final check through the
                # same queue as everything else, so it lands in the
                # daemon's e2e histogram — time it client-side so the
                # merged crosscheck sample covers the same population
                s.rec["close_latency"] = time.monotonic() - t0c
            else:
                s.rec["errors"] += 1
                s.rec["final"] = f"close-error-{code}"
            with cond:
                results.append(s.rec)
                done[0] += 1
                cond.notify_all()
            return
        block = s.plan["blocks"][s.seq]
        if not s.retried:
            s.t0 = time.monotonic()
        s.seq += 1
        code, r = _post_json(
            url, f"/session/{s.sid}/append",
            {"history": block, "seq": s.seq, "wait-s": sync_wait})
        if code == 429 and not s.retried:
            # backpressure: retry once after the advised delay
            s.retried = True
            s.seq -= 1
            _push(idx, time.monotonic()
                  + float(r.get("retry-after-s", 1.0)))
            return
        if code == 202 and r.get("id"):
            # slow (or async wait-s: 0) dispatch: protocol-legal —
            # the verdict arrives via GET /check/<id>
            s.pending = r["id"]
            s.deadline = time.monotonic() + wait_s
            _push(idx, time.monotonic() + poll_s)
            return
        if code != 200:
            _fail_block(s, idx)
            return
        _settle(s, idx, r)

    def worker() -> None:
        while True:
            with cond:
                while True:
                    if done[0] >= len(plans):
                        return
                    now = time.monotonic()
                    if heap and heap[0][0] <= now:
                        _due, _t, idx = heapq.heappop(heap)
                        break
                    cond.wait(max(0.005,
                                  (heap[0][0] - now) if heap
                                  else 0.1))
            try:
                _step(idx)
            except Exception:                           # noqa: BLE001
                s = states[idx]
                s.rec["errors"] += 1
                s.rec["final"] = "driver-error"
                with cond:
                    results.append(s.rec)
                    done[0] += 1
                    cond.notify_all()

    mux_before = {k: fetch_counter(url, k) for k in _MUX_COUNTERS}
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(nworkers)]
    t0 = time.monotonic()
    for i in range(len(plans)):
        _push(i, t0)
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    wall = max(1e-9, time.monotonic() - t0)
    mux_after = {k: fetch_counter(url, k) for k in _MUX_COUNTERS}
    cap_probe = probe_tenant_cap(url)
    lats = sorted(x for r in results for x in r["latencies"])
    mismatches = [r for r in results
                  if r["final"] is not r["expect"]]
    # a VALID stream flagged mid-run is a false alarm — as much a
    # verdict bug as a wrong close
    false_alarms = [r for r in results
                    if r["expect"] and r["flagged_at"] is not None]
    total_ops = sum(len(b) for p in plans for b in p["blocks"])
    n_appends = sum(r["appends"] for r in results)
    return {
        "sessions": len(plans),
        "appends": n_appends,
        "append_ops": total_ops,
        "errors": sum(r["errors"] for r in results),
        "wall_s": round(wall, 3),
        "sustained_append_ops_s": round(total_ops / wall, 1),
        "sustained_appends_s": round(n_appends / wall, 1),
        "mux": _mux_efficiency(mux_before, mux_after),
        "append_p50_s": (round(_percentile(lats, 0.50), 4)
                         if lats else None),
        "append_p99_s": (round(_percentile(lats, 0.99), 4)
                         if lats else None),
        # raw client samples for the merged histogram crosscheck
        # (appends AND closes ride the shared dispatch queue, so both
        # populations appear in the daemon's e2e histogram);
        # run_loadgen strips these before the report prints
        "_append_lats": lats,
        "_close_lats": sorted(
            r["close_latency"] for r in results
            if isinstance(r.get("close_latency"), (int, float))),
        "verdict_mismatches": len(mismatches),
        "false_alarms": len(false_alarms),
        "violating_sessions": sum(1 for r in results
                                  if not r["expect"]),
        "flagged_before_close": sum(
            1 for r in results
            if not r["expect"] and r["flagged_at"] is not None),
        "tenant_cap_probe": cap_probe,
    }


def probe_tenant_cap(url: str,
                     max_probe: int = 16) -> Optional[Dict[str, Any]]:
    """Assert the per-tenant open-session cap is ENFORCED: open empty
    sessions on one throwaway tenant until the daemon answers 429
    with cause ``tenant-cap``, then close them all. Skipped (None)
    when the daemon advertises no finite cap or it is larger than
    ``max_probe`` (probing a 64-cap daemon with 65 opens is not a
    smoke test's business); the ``enforced`` bit rides into the
    loadgen exit gate."""
    code, stats = _get(url, "/stats")
    cap = ((stats.get("sessions") or {}).get("tenant-cap")
           if code == 200 else None)
    if not cap or int(cap) > max_probe:
        return None
    cap = int(cap)
    opened: List[str] = []
    hit = None
    for _ in range(cap + 1):
        code, resp = _post_json(url, "/session",
                                {"model": "cas-register",
                                 "tenant": "cap-probe"})
        if code == 201:
            opened.append(resp["session"])
        elif code == 429:
            hit = resp
            break
        else:
            break
    for sid in opened:
        _post_json(url, f"/session/{sid}/close", {})
    enforced = (hit is not None
                and hit.get("cause") == "tenant-cap"
                and len(opened) == cap)
    return {"cap": cap, "opened": len(opened),
            "cause": (hit or {}).get("cause"), "enforced": enforced}


def _post_json(url: str, path: str, payload: Dict) -> Tuple[int, Dict]:
    # one transport ladder for the toolbox: delegate to _post (the
    # longer timeout covers synchronous session appends/closes)
    return _post(url, json.dumps(payload).encode(), path=path,
                 timeout=120.0)


def run_loadgen(opts: Dict[str, Any]) -> Dict[str, Any]:
    """Programmatic entry (bench.py's ``serve`` sub-object): ``opts``
    mirrors the CLI flags. Self-hosts a daemon when no url given."""
    quick = bool(opts.get("quick"))
    rate = float(opts.get("rate") or (8.0 if quick else 20.0))
    duration = float(opts.get("duration") or (4.0 if quick else 20.0))
    tenants = int(opts.get("tenants") or 4)
    sizes = opts.get("sizes") or ([16, 32, 48] if quick
                                  else [32, 96, 200, 400])
    if opts.get("mixed_consistency"):
        # transactional pool: every payload is a txn history with a
        # known per-level lattice ground truth, submitted at one
        # requested level (levels round-robin across the pool)
        pool = build_txn_pool(tenants=tenants,
                              seed=int(opts.get("seed", 7)),
                              clean_sizes=((8, 16) if quick
                                           else (12, 30, 60)))
    else:
        pool = build_pool(sizes=sizes, tenants=tenants,
                          violation_frac=float(
                              opts.get("violation_frac", 0.25)),
                          model=opts.get("model", "cas-register"),
                          seed=int(opts.get("seed", 7)))
    url = opts.get("url")
    replicas = [u for u in (opts.get("replicas") or []) if u]
    if replicas:
        # fleet mode: client-side round-robin over the replica list;
        # the first replica doubles as the primary for warmup-era
        # probes and the stats scrape
        url = replicas[0]
    n_sessions = int(opts.get("n_sessions")
                     or (2 if quick else 4)) \
        if opts.get("sessions") else 0
    daemon = None
    if not url:
        from jepsen_tpu import serve
        # thousand-session mux runs need queue room for every live
        # stream's one in-flight append (that backlog IS the lane
        # supply the mega dispatch multiplexes) — scaled only above
        # the default so small runs keep the historical bound
        qd = max(256, 2 * n_sessions)
        daemon = serve.Daemon(port=int(opts.get("port") or 0),
                              host="127.0.0.1",
                              group=int(opts.get("group")
                                        or (8 if quick else 32)),
                              queue_depth=qd,
                              store_root=opts.get("store_root"),
                              persist=bool(opts.get("store_root")),
                              # small cap so probe_tenant_cap can
                              # assert enforcement with a handful of
                              # empty opens
                              session_tenant_cap=8).start()
        url = f"http://127.0.0.1:{daemon.port}"
    report: Dict[str, Any] = {}
    try:
        for u in (replicas or [url]):
            if not wait_ready(u, timeout=float(
                    opts.get("ready_timeout", 60.0))):
                report["error"] = f"daemon at {u} never became ready"
                return report
        if opts.get("warmup", True):
            burst = int(opts.get("warm_burst")
                        or (8 if quick else 16))
            if replicas:
                # every replica compiles its own kernel geometries:
                # an unwarmed sibling would bill its compile wall to
                # the measured windows and sink the scaling number
                report["warmup"] = {u: warmup(u, pool, burst=burst)
                                    for u in replicas}
            else:
                report["warmup"] = warmup(url, pool, burst=burst)
        # scrape the e2e histogram around the measured run: the delta
        # is the measured window's distribution, warmup excluded
        hist_before = fetch_hist_buckets(url)
        sess_result: Dict[str, Any] = {}
        sess_thread = None
        if opts.get("sessions"):
            # mixed traffic: long-lived sessions append at their
            # cadence WHILE the one-shot open-loop load runs — the
            # coalescer interleaves append groups with check groups,
            # which is the serving regime sessions actually face
            # tenant spread: the per-tenant open-session cap (8 on
            # the self-hosted daemon) must clear, and the per-tenant
            # in-flight allowance must not throttle the mux lanes
            sess_tenants = (opts.get("session_tenants")
                            or (2 if n_sessions <= 16
                                else max(2, -(-n_sessions // 6))))
            plans = build_session_plans(
                n_sessions=n_sessions,
                ops_per_session=int(opts.get("session_ops")
                                    or (240 if quick else 2000)),
                appends=int(opts.get("session_appends")
                            or (6 if quick else 12)),
                violation_frac=float(
                    opts.get("violation_frac", 0.25)),
                seed=int(opts.get("seed", 7)),
                tenants=int(sess_tenants))

            def _run_sessions() -> None:
                sess_result.update(run_session_traffic(
                    url, plans,
                    cadence_s=float(opts.get("session_cadence")
                                    or 0.1),
                    workers=opts.get("session_workers")))
            sess_thread = threading.Thread(target=_run_sessions,
                                           daemon=True)
            sess_thread.start()
        report.update(run_load(
            url, rate=rate, duration=duration, pool=pool,
            chaos_tolerant=bool(opts.get("chaos_tolerant")),
            urls=replicas or None))
        if sess_thread is not None:
            sess_thread.join(600)
            report["sessions"] = sess_result
        # the raw client samples exist only to feed the merged
        # crosscheck below — pull them out of the report (they'd
        # bloat every printed run, and thousand-session runs carry
        # tens of thousands of floats)
        one_shot_lats = report.pop("_admit_lats", None) or []
        sess_lats = []
        if isinstance(report.get("sessions"), dict):
            sess_lats = list(report["sessions"]
                             .pop("_append_lats", None) or [])
            sess_lats += list(report["sessions"]
                              .pop("_close_lats", None) or [])
        if replicas:
            # fleet summary: merged throughput over N replicas, and
            # the scaling efficiency against a caller-provided
            # 1-replica baseline (req/s at N / (N * req/s at 1))
            fleet: Dict[str, Any] = {
                "replicas": len(replicas),
                "per_replica": report.get("per_replica")}
            base = opts.get("baseline_req_s")
            if base:
                fleet["baseline_req_s"] = float(base)
                fleet["scaling_efficiency"] = round(
                    report.get("sustained_req_s", 0.0)
                    / (len(replicas) * float(base)), 3)
            report["fleet"] = fleet
            # the per-process daemon histograms cannot be compared
            # against the MERGED client quantiles: skip the
            # crosscheck in fleet mode (each replica's own histogram
            # stays scrapeable via its /metrics)
            _flag_saturation(report, rate)
            report["url"] = url
            return report
        hist_after = fetch_hist_buckets(url)
        # cross-check against the ADMISSION-anchored quantiles: the
        # daemon histogram measures admit->terminal, while the
        # client-side p99 additionally carries submission-side
        # blocking under a saturated queue (the BENCH_r06 failure:
        # loadgen 39.2 s vs histogram 12.4 s was ~27 s of pre-admit
        # wait the daemon never saw) — see SERVING.md
        # mixed-traffic runs (--sessions): the shared e2e histogram
        # records one-shots AND session appends AND closes, so the
        # one-shot quantiles alone compare a sub-population against
        # the whole (at mux scale the appends dominate and the
        # crosscheck fails spuriously) — merge the client-side
        # samples so both sides cover the same requests
        if sess_lats:
            merged = sorted(one_shot_lats + sess_lats)
            lg_q = {"p50": _percentile(merged, 0.50),
                    "p99": _percentile(merged, 0.99)}
        else:
            lg_q = {"p50": report.get("p50_admit_s"),
                    "p99": report.get("p99_admit_s")}
        xc = crosscheck_quantiles(lg_q, hist_before, hist_after)
        if xc is not None:
            xc["anchor"] = ("admission+session-stream" if sess_lats
                            else "admission")
            # queue-overloaded regime (sustained throughput well
            # below the offered rate, or admissions refused): the
            # tail is backlog — the client's p99 additionally carries
            # GIL/scheduler starvation of hundreds of in-flight
            # pollers, which the daemon histogram (admit->terminal on
            # the dispatch thread) never contains. The p99 gate is
            # WAIVED there (p50 stays binding — a mid-distribution
            # clock/stamping bug still fails); see SERVING.md.
            qw = (report.get("stage_split") or {}) \
                .get("queue_wait") or {}
            overloaded = (
                # the daemon's own queue-wait split IS the regime
                # signal: a healthy run queues for milliseconds — a
                # MEDIAN wait past 0.5 s means the open-loop client
                # outran the daemon (backlog), and a tail stretched
                # far past the median means transient backlog bursts
                (qw.get("p50_s") or 0.0) > 0.5
                or (qw.get("p99_s") or 0.0)
                > max(1.0, 4.0 * (qw.get("p50_s") or 0.0))
                or report.get("sustained_req_s", rate) < 0.7 * rate
                or report.get("rejected_429", 0) > 0)
            p99g = xc.get("p99") or {}
            p50_ok = (xc.get("p50") or {}).get("ok")
            if (overloaded and p99g.get("ok") is False
                    and p50_ok is not False):
                xc["p99_gate"] = "waived-queue-overloaded"
                xc["ok"] = True
            report["latency_crosscheck"] = xc
        _flag_saturation(report, rate)
        if opts.get("find_capacity"):
            # capacity search AFTER the crosscheck scrape: its probe
            # traffic must not leak into the measured window's
            # histogram delta
            try:
                report["capacity"] = find_capacity(
                    url, pool, quick=quick,
                    start_rate=float(opts.get("capacity_start")
                                     or max(4.0, rate / 2.0)))
            except Exception as e:                      # noqa: BLE001
                report["capacity"] = {
                    "error": f"{type(e).__name__}: {e}"}
        report["url"] = url
        return report
    finally:
        if daemon is not None:
            report["drained"] = daemon.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop load generator for the jepsen-tpu "
                    "check daemon")
    ap.add_argument("--url", default=None,
                    help="daemon base url; omitted = --self-host")
    ap.add_argument("--replicas", default=None,
                    help="fleet mode: comma-separated replica base "
                         "urls; submissions round-robin client-side "
                         "and the report carries per-replica req/s")
    ap.add_argument("--baseline-req-s", type=float, default=None,
                    help="1-replica sustained req/s baseline; with "
                         "--replicas the report then carries "
                         "scaling_efficiency = req_s_at_N / "
                         "(N * baseline)")
    ap.add_argument("--self-host", action="store_true",
                    help="start an in-process daemon on an ephemeral "
                         "port")
    ap.add_argument("--rate", type=float, default=None,
                    help="target arrival rate, req/s")
    ap.add_argument("--duration", type=float, default=None,
                    help="run length, seconds (two measurement "
                         "windows of half each)")
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--model", default="cas-register")
    ap.add_argument("--mixed-consistency", action="store_true",
                    help="txn lattice pool: tenants submit "
                         "transactional histories at DIFFERENT "
                         "consistency levels through one coalescer; "
                         "the exit gate asserts every per-level "
                         "holds verdict against the fixture ground "
                         "truth (overrides --model)")
    ap.add_argument("--violation-frac", type=float, default=0.25)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--store-root", default=None,
                    help="self-hosted daemon persistence root")
    ap.add_argument("--quick", action="store_true",
                    help="small CI run: low rate, short duration, "
                         "tiny histories")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip the cold-start warmup phase (measure "
                         "the compile wall inside the windows)")
    ap.add_argument("--chaos-tolerant", action="store_true",
                    help="expect a scripted daemon kill/restart: "
                         "retry refused POSTs, record refusals as "
                         "error-restart (not error-net), keep "
                         "polling across the gap, and report "
                         "recovery-time-to-first-verdict")
    ap.add_argument("--sessions", action="store_true",
                    help="mix long-lived streaming sessions into the "
                         "load (appends at --session-cadence) and "
                         "gate their incremental + close verdicts "
                         "against ground truth, reporting the "
                         "per-append latency distribution")
    ap.add_argument("--session-cadence", type=float, default=0.1,
                    help="seconds between one session's appends")
    ap.add_argument("--n-sessions", type=int, default=None,
                    help="how many live sessions to drive (the "
                         "worker-pool driver scales to 5000+; "
                         "default 2 with --quick, else 4)")
    ap.add_argument("--session-ops", type=int, default=None,
                    help="ops per session stream (default 240 with "
                         "--quick, else 2000)")
    ap.add_argument("--session-appends", type=int, default=None,
                    help="append blocks per session (default 6 with "
                         "--quick, else 12)")
    ap.add_argument("--session-workers", type=int, default=None,
                    help="driver worker threads for session traffic "
                         "(default: min(64, n_sessions))")
    ap.add_argument("--find-capacity", action="store_true",
                    help="after the fixed-rate run, binary-search the "
                         "offered rate to the daemon's max sustained "
                         "req/s and report it under 'capacity'")
    args = ap.parse_args(argv)
    if args.self_host and args.url:
        ap.error("--self-host and --url are mutually exclusive")
    if args.replicas and (args.self_host or args.url):
        ap.error("--replicas is mutually exclusive with "
                 "--url/--self-host")
    report = run_loadgen({
        "url": args.url,
        "replicas": ([u.strip() for u in args.replicas.split(",")
                      if u.strip()] if args.replicas else None),
        "baseline_req_s": args.baseline_req_s,
        "rate": args.rate,
        "duration": args.duration, "tenants": args.tenants,
        "model": args.model, "violation_frac": args.violation_frac,
        "mixed_consistency": args.mixed_consistency,
        "seed": args.seed, "store_root": args.store_root,
        "quick": args.quick, "warmup": not args.no_warmup,
        "chaos_tolerant": args.chaos_tolerant,
        "sessions": args.sessions,
        "session_cadence": args.session_cadence,
        "n_sessions": args.n_sessions,
        "session_ops": args.session_ops,
        "session_appends": args.session_appends,
        "session_workers": args.session_workers,
        "find_capacity": args.find_capacity,
    })
    print(json.dumps(report, default=str))
    if report.get("error"):
        return 2
    ok = (report.get("completed", 0) > 0
          and report.get("verdict_mismatches", 0) == 0)
    # session gate: every close verdict equals its stream's ground
    # truth, no valid stream was ever flagged mid-run, no transport
    # errors — the streaming protocol's correctness bar
    sess = report.get("sessions")
    if sess is not None:
        if (sess.get("verdict_mismatches", 0)
                or sess.get("false_alarms", 0)
                or sess.get("errors", 0)
                or sess.get("appends", 0) == 0):
            ok = False
        # per-tenant cap: when the daemon advertises a probe-able
        # cap, the 429/tenant-cap refusal must actually fire
        cp = sess.get("tenant_cap_probe")
        if cp is not None and not cp.get("enforced"):
            ok = False
    # the histogram cross-check catches clock/stamping bugs: loadgen's
    # client-measured quantiles and the daemon's histogram-derived
    # ones must agree (>15% past the poll-resolution slack is a bug)
    xc = report.get("latency_crosscheck")
    if xc is not None and xc.get("ok") is False:
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
