"""Run the full BASELINE.md benchmark ladder and print one JSON line per
rung (engine comparison: device reach, chunked, native C++, Python WGL).

Usage: python tools/ladder.py [--quick]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def time_engine(fn, repeat: int = 2):
    fn()                                    # warm-up / compile
    best = float("inf")
    for _ in range(repeat):
        t0 = time.monotonic()
        res = fn()
        best = min(best, time.monotonic() - t0)
    return res, best


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="shrink the big rungs for CI")
    args = ap.parse_args()

    from jepsen_tpu import fixtures, independent, models
    from jepsen_tpu.checkers import reach, wgl_native, wgl_ref
    from jepsen_tpu.history import pack

    scale = 10 if args.quick else 1
    rungs = [
        ("register-200", "register", 200 // scale or 20, 5),
        ("cas-1k", "cas", 1_000 // scale, 5),
        ("mutex-5k", "mutex", 5_000 // scale, 5),
        ("multi-10k", "multi", 10_000 // scale, 5),
        ("cas-100k", "cas", 100_000 // scale, 5),
    ]
    for name, kind, n_ops, procs in rungs:
        hist = fixtures.gen_history(kind, n_ops=n_ops, processes=procs,
                                    seed=42)
        packed = pack(hist)
        model = fixtures.model_for(kind)
        row = {"rung": name, "ops": n_ops}
        res, dt = time_engine(lambda: reach.check_packed(model, packed))
        assert res["valid"] is True, (name, res)
        row["reach_s"] = round(dt, 4)
        try:
            res, dt = time_engine(
                lambda: reach.check_chunked(model, packed=packed,
                                            n_chunks=64,
                                            max_matrix=1 << 28))
            assert res["valid"] is True, (name, res)
            row["chunked_s"] = round(dt, 4)
        except Exception as e:                          # noqa: BLE001
            row["chunked_s"] = f"n/a ({type(e).__name__})"
        if wgl_native.available():
            res, dt = time_engine(
                lambda: wgl_native.check_packed(model, packed))
            assert res["valid"] is True, (name, res)
            row["native_s"] = round(dt, 4)
        if n_ops <= 10_000:
            res, dt = time_engine(
                lambda: wgl_ref.check_packed(model, packed,
                                             time_limit=120),
                repeat=1)
            row["wgl_py_s"] = (round(dt, 4) if res["valid"] is True
                               else f"{res['valid']}")
        print(json.dumps(row), flush=True)

    # round-4 batch rungs: H independent cas-100k histories, the
    # lockstep batch kernel (ONE device walk per dispatch group) vs
    # the C++ engine looping them on one core — the aggregate-
    # throughput comparison (BASELINE.md round-4 batch section). H=8
    # is the original recorded rung; H=32 is one full-width dispatch
    # group at the adaptive-block default.
    n_ops = 100_000 // scale
    model = fixtures.model_for("cas")
    widths = (8,) if args.quick else (8, 32)    # one rung is enough for CI
    all_packed = [fixtures.gen_packed("cas", n_ops=n_ops, processes=5,
                                      seed=100 + s)
                  for s in range(max(widths))]
    for H in widths:
        packeds = all_packed[:H]
        row = {"rung": f"cas-{n_ops // 1000}k-x{H}", "ops": n_ops * H}
        res, dt = time_engine(lambda: reach.check_batch(model, packeds))
        assert all(r["valid"] is True for r in res), (row["rung"], res)
        row["reach_batch_s"] = round(dt, 4)
        row["reach_batch_ops_s"] = round(n_ops * H / dt)
        if wgl_native.available():
            def _cpp_all():
                out = [wgl_native.check_packed(model, p) for p in packeds]
                assert all(r["valid"] is True for r in out), row["rung"]
                return out
            res, dt = time_engine(_cpp_all)
            row["native_s"] = round(dt, 4)
            row["native_ops_s"] = round(n_ops * H / dt)
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
