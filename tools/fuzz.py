"""Cross-engine differential fuzzer (SURVEY.md §4: the rebuild's answer
to knossos's recorded-fixture cross-checks — thousands of randomized
small histories, every engine must agree).

Each trial draws a random workload kind, concurrency, crash rate, and
possibly an injected violation, then runs every applicable engine:

- ``wgl_ref``   — readable Python WGL (the oracle)
- ``linear``    — sparse JIT-linearization (array/set config sets)
- ``wgl-native``— C++ memoized DFS
- ``reach``     — the dense device engine (XLA walk; pass ``--pallas`` to
  also run the fused kernel in interpret mode — slow but exact)
- ``frontier``  — the sparse batched-frontier device engine (crashed-op
  quotient), skipped on capacity overflow
- ``decompose`` — P-compositional per-key split (multi-register
  workloads with single-key ops only)
- ``brute``     — exhaustive permutation check on tiny histories

Disagreement on a verdict (True/False; ``"unknown"`` is inconclusive and
excluded) is a bug in one of them. Exit code 1 on any mismatch.

Usage: python tools/fuzz.py [--n 1000] [--seed 0] [--pallas] [-v]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KINDS = ("register", "cas", "mutex", "multi")


def trial_params(rng: random.Random):
    kind = rng.choice(KINDS)
    return {
        "kind": kind,
        "n_ops": rng.randrange(4, 60),
        "processes": rng.randrange(2, 6),
        "values": rng.choice((2, 3, 5)),
        "crash_p": rng.choice((0.0, 0.0, 0.05, 0.2)),
        "keys": rng.randrange(2, 4) if kind == "multi" else 1,
        "corrupt": rng.random() < 0.5,
    }


def run_trial(params, seed: int, *, pallas: bool = False):
    """Returns (verdicts dict, mismatch bool)."""
    from jepsen_tpu import fixtures
    from jepsen_tpu.checkers import brute, linear, reach, wgl_native, wgl_ref
    from jepsen_tpu.history import pack

    h = fixtures.gen_history(
        params["kind"], n_ops=params["n_ops"],
        processes=params["processes"], values=params["values"],
        crash_p=params["crash_p"], keys=params["keys"], seed=seed)
    if params["corrupt"]:
        try:
            h = fixtures.corrupt(h, seed=seed)
        except ValueError:          # no reads (e.g. mutex): leave valid
            pass
    model = fixtures.model_for(params["kind"])
    packed = pack(h)

    from jepsen_tpu.checkers.events import ConcurrencyOverflow
    from jepsen_tpu.models.memo import StateExplosion

    verdicts = {}
    verdicts["wgl_ref"] = wgl_ref.check_packed(
        model, packed, time_limit=60)["valid"]
    verdicts["linear"] = linear.check_packed(
        model, packed, max_configs=2_000_000)["valid"]
    if wgl_native.available():
        verdicts["wgl-native"] = wgl_native.check_packed(
            model, packed)["valid"]
    try:
        # capacity overflows are legitimate skips; anything else (an
        # engine CRASH) must propagate — hiding it would defeat the fuzz
        verdicts["reach"] = reach.check_packed(model, packed)["valid"]
    except (reach.DenseOverflow, ConcurrencyOverflow, StateExplosion) as e:
        verdicts["reach"] = f"skipped: {type(e).__name__}"
    try:
        from jepsen_tpu.checkers import frontier
        verdicts["frontier"] = frontier.check_packed(
            model, packed, frontier0=64)["valid"]
    except (frontier.FrontierOverflow, ConcurrencyOverflow,
            StateExplosion) as e:
        verdicts["frontier"] = f"skipped: {type(e).__name__}"
    try:
        # the length-parallel engine (forward-pass basis restriction +
        # restricted transfer-matrix fold) is its own walk composition
        verdicts["reach-chunked"] = reach.check_chunked(
            model, packed=packed, n_chunks=4)["valid"]
    except (reach.DenseOverflow, ConcurrencyOverflow,
            StateExplosion) as e:
        verdicts["reach-chunked"] = f"skipped: {type(e).__name__}"
    if params["kind"] == "multi":
        from jepsen_tpu.checkers import decompose
        d = decompose.check(model, h)
        verdicts["decompose"] = (d["valid"] if d is not None
                                 else "skipped: not-decomposable")
    from jepsen_tpu.checkers import reach_q
    try:
        # the sparse-live quotient walk (round-5 epoch-rank
        # canonicalization) — max_dense=256 forces the sparse rows
        # wherever the dense product would otherwise absorb the trial
        from jepsen_tpu.checkers import events as _ev
        from jepsen_tpu.models.memo import memo as _build_memo
        memo_q = _build_memo(model, packed, max_states=100_000)
        stream_q = _ev.build(packed, memo_q, max_slots=128)
        verdicts["reach-q-sparse"] = reach_q.check_quotient(
            memo_q, stream_q, packed, max_dense=1 << 8)["valid"]
    except (reach_q.QuotientOverflow, ConcurrencyOverflow,
            StateExplosion) as e:
        verdicts["reach-q-sparse"] = f"skipped: {type(e).__name__}"
    if pallas:
        try:
            from jepsen_tpu.checkers import events as ev
            from jepsen_tpu.checkers import reach_lane, reach_pallas
            memo, stream, T, S_pad, M = reach._prep(
                model, packed, max_states=100_000, max_slots=20,
                max_dense=1 << 22)
            rs = ev.returns_view(stream)
            import numpy as np
            P = reach._build_P(memo, S_pad)
            R0 = np.zeros((S_pad, M), bool)
            R0[0, 0] = True
            dead, _ = reach_pallas.walk_returns(
                P, rs.ret_slot, rs.slot_ops, R0, interpret=True,
                fetch_R=False)
            verdicts["reach-pallas"] = dead < 0
        except Exception as e:                          # noqa: BLE001
            verdicts["reach-pallas"] = f"skipped: {type(e).__name__}"
        else:
            # separate guard: a lane failure must not discard the
            # already-computed first-generation verdict
            try:
                dead2, _ = reach_lane.walk_returns(
                    P, rs.ret_slot, rs.slot_ops, R0, interpret=True,
                    fetch_R=False)
                verdicts["reach-lane"] = dead2 < 0
            except Exception as e:                      # noqa: BLE001
                verdicts["reach-lane"] = f"skipped: {type(e).__name__}"
            # chunk-lockstep (round-5): tiny chunk/seed/suffix geometry
            # exercises the bound pass, union seeds, fold, and rescue
            try:
                from jepsen_tpu.checkers import reach_chunklock as rcl
                dead3, _d = rcl.walk_chunklock(
                    P, rs.ret_slot, rs.slot_ops, M, n_chunks=3,
                    e_pad=2, suffix=6, interpret=True)
                verdicts["reach-chunklock"] = dead3 < 0
            except Exception as e:                      # noqa: BLE001
                verdicts["reach-chunklock"] = \
                    f"skipped: {type(e).__name__}"
        # lockstep batch kernel: walk THIS history alongside a fresh
        # companion of the same workload (heterogeneous lockstep — the
        # cross-history-independence property under test). The entry
        # mirrors the main verdict; a companion whose lockstep verdict
        # disagrees with its own reference FLIPS it so the mismatch
        # machinery fires.
        try:
            from jepsen_tpu import fixtures as fx
            from jepsen_tpu.checkers import reach_batch
            from jepsen_tpu.history import pack as _pack
            h2 = fx.gen_history(params["kind"],
                                n_ops=params["n_ops"],
                                processes=params["processes"],
                                seed=seed + 7_777_777)
            if params.get("corrupt") and seed % 2:
                try:
                    h2 = fx.corrupt(h2, seed=seed + 1)
                except ValueError:
                    pass
            packed2 = _pack(h2)
            ref2 = reach.check_packed(model, packed2)["valid"]
            pair = [packed, packed2]
            preps = [reach._prep(model, p, max_states=100_000,
                                 max_slots=20, max_dense=1 << 22)
                     for p in pair]
            Wp = max(max(pr[1].W, 1) for pr in preps)
            Mp = 1 << Wp
            rss = [ev.returns_view(pr[1]) for pr in preps]
            Pp, ret_flat, ops_flat, _kf, offsets, _wide = \
                reach._keyed_operands(model, pair, rss, [0, 1], Wp,
                                      100_000)
            deadb = reach_batch.walk_returns_batch(
                Pp,
                [ret_flat[offsets[k]:offsets[k + 1]] for k in (0, 1)],
                [ops_flat[offsets[k]:offsets[k + 1]] for k in (0, 1)],
                Mp, interpret=True)
            main_v = bool(deadb[0] < 0)
            companion_ok = (deadb[1] < 0) == (ref2 is True)
            verdicts["reach-batch"] = (main_v if companion_ok
                                       else not main_v)
        except Exception as e:                          # noqa: BLE001
            verdicts["reach-batch"] = f"skipped: {type(e).__name__}"
    # the incremental monitor is a third implementation of the dense
    # walk (host NumPy, settled-prefix advance): feed it the raw stream
    try:
        from jepsen_tpu.checkers.online import IncrementalEngine, _Overflow
        eng = IncrementalEngine(model)
        v = None
        for op in h:
            eng.feed(op)
        v = eng.advance(run_over=True)
        verdicts["online-inc"] = v is None
    except _Overflow as e:
        verdicts["online-inc"] = f"skipped: {type(e).__name__}"
    # the C++ streaming monitor core is a fourth independent
    # implementation of the dense walk's bookkeeping
    try:
        from jepsen_tpu.checkers import preproc_native
        from jepsen_tpu.checkers.online import (NativeStreamEngine,
                                                _Overflow)
        if preproc_native.available():
            eng2 = NativeStreamEngine(model)
            eng2.feed_many(list(h))
            v2 = eng2.advance(run_over=True)
            verdicts["online-native"] = v2 is None
    except _Overflow as e:
        verdicts["online-native"] = f"skipped: {type(e).__name__}"
    if packed.n <= 7:
        verdicts["brute"] = brute.check(model, h)["valid"]

    conclusive = {k: v for k, v in verdicts.items()
                  if isinstance(v, bool)}
    mismatch = len({bool(v) for v in conclusive.values()}) > 1
    return verdicts, mismatch


def run_many(n: int, seed: int, *, pallas: bool = False,
             verbose: bool = False):
    """Run ``n`` trials; returns ``(mismatches, invalid_seen)`` where
    ``mismatches`` is a list of {trial, seed, params, verdicts} dicts.
    Shared by the CLI below and the CI slice in tests/test_fuzz.py."""
    rng = random.Random(seed)
    t0 = time.monotonic()
    mismatches = []
    invalid_seen = 0
    for t in range(n):
        params = trial_params(rng)
        trial_seed = rng.randrange(1 << 30)
        verdicts, bad = run_trial(params, trial_seed, pallas=pallas)
        if any(v is False for v in verdicts.values()):
            invalid_seen += 1
        if verbose:
            print(f"trial {t}: {params['kind']} n={params['n_ops']} "
                  f"-> {verdicts}", flush=True)
        if bad:
            mismatches.append({"trial": t, "seed": trial_seed,
                               "params": params, "verdicts": verdicts})
            print(f"MISMATCH trial {t}: {params} seed={trial_seed} "
                  f"-> {verdicts}", file=sys.stderr)
        elif t % 25 == 24:
            # checkpoint progress unconditionally: XLA-CPU's JIT
            # intermittently dies of "LLVM compilation error: Cannot
            # allocate memory" on long runs, and a crash at trial N
            # must not erase the N-1 clean results
            print(f"progress {t + 1}/{n} ok, {invalid_seen} invalid "
                  f"({time.monotonic() - t0:.0f}s)", flush=True)
    return mismatches, invalid_seen


def _seq_reach(model, packed):
    """Sequential dense-walk reference with chunklock disabled,
    preserving any operator-set ``JEPSEN_TPU_NO_CHUNKLOCK`` value
    (unconditionally deleting it mid-run clobbered the operator's
    setting for every later trial)."""
    prev = os.environ.get("JEPSEN_TPU_NO_CHUNKLOCK")
    os.environ["JEPSEN_TPU_NO_CHUNKLOCK"] = "1"
    try:
        from jepsen_tpu.checkers import reach
        return reach.check_packed(model, packed)
    finally:
        if prev is None:
            os.environ.pop("JEPSEN_TPU_NO_CHUNKLOCK", None)
        else:
            os.environ["JEPSEN_TPU_NO_CHUNKLOCK"] = prev


def chunklock_trials(k: int, seed: int) -> list:
    """Real-chip chunk-lockstep differential: ``k`` engine-scale
    histories (the routing floor is 32768 returns, so these run the
    COMPILED production engine, not interpret mode) checked by
    walk-level chunklock vs the C++ WGL engine — verdicts AND dead
    events must agree. Sizes are fixed so one compile serves all
    trials. Returns mismatch dicts (empty = clean)."""
    from jepsen_tpu import fixtures
    from jepsen_tpu.checkers import reach_chunklock as rcl
    from jepsen_tpu.checkers import wgl_native

    rng = random.Random(seed)
    bad = []
    t0 = time.monotonic()
    for t in range(k):
        kind = rng.choice(("cas", "register", "mutex"))
        s = rng.randrange(1 << 30)
        packed = fixtures.gen_packed(kind, n_ops=33_000, processes=5,
                                     seed=s)
        corrupt = rng.random() < 0.5
        if corrupt:
            h = fixtures.gen_history(kind, n_ops=33_000, processes=5,
                                     seed=s)
            try:
                h = fixtures.corrupt(h, seed=s)
            except ValueError:
                corrupt = False
            else:
                from jepsen_tpu.history import pack as _pack
                packed = _pack(h)
        model = fixtures.model_for(kind)
        res = rcl.check_packed(model, packed)
        ref = (wgl_native.check_packed(model, packed)
               if wgl_native.available() else None)
        entry = {"trial": t, "seed": s, "kind": kind,
                 "corrupt": corrupt, "chunklock": res["valid"],
                 "rescues": res.get("rescues")}
        ok = True
        if ref is not None:
            # verdicts must agree with the C++ engine; witness OPS are
            # engine-convention (the DFS legitimately stops at a
            # different unlinearizable op than first-empty-return)
            entry["wgl-native"] = ref["valid"]
            ok = res["valid"] == ref["valid"]
        elif res["valid"] is True:
            # no C++ engine built: True verdicts previously went
            # entirely unreferenced — cross-check them against the
            # sequential dense walk instead
            seq = _seq_reach(model, packed)
            entry["reach"] = seq["valid"]
            ok = seq["valid"] is True
        if ok and res["valid"] is False:
            # dead-event must be BIT-IDENTICAL to the sequential
            # dense walk (same first-empty-return semantics)
            seq = _seq_reach(model, packed)
            entry["reach"] = seq["valid"]
            ok = (seq["valid"] is False
                  and res.get("dead-event") == seq.get("dead-event"))
        if not ok:
            bad.append(entry)
            print(f"CHUNKLOCK MISMATCH {entry}", file=sys.stderr)
        if t % 10 == 9:
            print(f"chunklock {t + 1}/{k} ok "
                  f"({time.monotonic() - t0:.0f}s)", flush=True)
    return bad


def txn_trials(k: int, seed: int) -> list:
    """Transactional-checker differential: ``k`` random list-append
    histories — roughly half with an injected ww/wr/rw cycle block of
    a known class (``fixtures.txn_anomaly_block``) — checked by the
    DEVICE closure engine and the host SCC reference on the same
    inferred graph. Anomaly lists AND witness cycles must be
    identical, and an injected class must be detected. Returns
    mismatch dicts (empty = clean)."""
    import random as _random

    from jepsen_tpu import fixtures, txn

    rng = _random.Random(seed)
    bad = []
    t0 = time.monotonic()
    for t in range(k):
        s = rng.randrange(1 << 30)
        n_txns = rng.randrange(10, 120)
        keys = rng.randrange(2, 5)
        crash_p = rng.choice((0.0, 0.0, 0.1))
        h = fixtures.gen_txn_history(n_txns, keys=keys, processes=5,
                                     crash_p=crash_p, seed=s)
        injected = None
        if rng.random() < 0.5:
            injected = rng.choice(fixtures.TXN_ANOMALY_KINDS)
            h = h + [op.with_(index=-1) for op in
                     fixtures.txn_anomaly_block(injected)]
        dev = txn.check_history(h)               # word-packed default
        os.environ["JEPSEN_TPU_NO_WORD_CLOSURE"] = "1"
        try:
            f32 = txn.check_history(h)           # f32 fallback body
        finally:
            os.environ.pop("JEPSEN_TPU_NO_WORD_CLOSURE", None)
        host = txn.check_history(h, force_host=True)
        entry = {"trial": t, "seed": s, "injected": injected,
                 "device": dev.get("anomalies"),
                 "f32": f32.get("anomalies"),
                 "host": host.get("anomalies"),
                 "engine": dev.get("engine")}
        ok = (dev.get("valid") == host.get("valid") == f32.get("valid")
              and dev.get("anomalies") == host.get("anomalies")
              and f32.get("anomalies") == host.get("anomalies")
              and dev.get("witness") == host.get("witness"))
        if injected is not None:
            ok = ok and injected in (dev.get("anomalies") or ())
        if not ok:
            bad.append(entry)
            print(f"TXN MISMATCH {entry}", file=sys.stderr)
        if t % 25 == 24:
            print(f"txn {t + 1}/{k} ok "
                  f"({time.monotonic() - t0:.0f}s)", flush=True)
    return bad


def lattice_trials(k: int, seed: int) -> list:
    """Consistency-lattice differential: ``k`` random list-append
    histories — roughly half with an injected lattice fixture block
    of documented per-level ground truth
    (``fixtures.TXN_LATTICE_KINDS``) — checked at EVERY lattice level
    in one dispatch by the word-packed device closure, the f32
    fallback body, and the host lattice reference. Per-level holds,
    anomaly lists AND witnesses must be identical across all three
    engines, and an injected block's documented weakest-violated
    level must be reported. Returns mismatch dicts (empty = clean)."""
    import random as _random

    from jepsen_tpu import fixtures, txn
    from jepsen_tpu.txn import lattice

    weakest = {"write-skew": "si", "lost-update": "read-committed",
               "long-fork": "si", "session-mr": "pl-2"}
    levels = list(lattice.LEVELS)
    rng = _random.Random(seed)
    bad = []
    t0 = time.monotonic()
    for t in range(k):
        s = rng.randrange(1 << 30)
        n_txns = rng.randrange(10, 100)
        keys = rng.randrange(2, 5)
        h = fixtures.gen_txn_history(n_txns, keys=keys, processes=5,
                                     seed=s)
        injected = None
        if rng.random() < 0.5:
            injected = rng.choice(fixtures.TXN_LATTICE_KINDS)
            h = h + [op.with_(index=-1) for op in
                     fixtures.txn_anomaly_block(injected)]
        dev = txn.check_history(h, consistency=levels)
        os.environ["JEPSEN_TPU_NO_WORD_CLOSURE"] = "1"
        try:
            f32 = txn.check_history(h, consistency=levels)
        finally:
            os.environ.pop("JEPSEN_TPU_NO_WORD_CLOSURE", None)
        host = txn.check_history(h, consistency=levels,
                                 force_host=True)

        def _sig(r):
            per = r.get("levels") or {}
            return (r.get("valid"), r.get("holds"),
                    r.get("weakest-violated"),
                    {lvl: ((per.get(lvl) or {}).get("anomalies"),
                           (per.get(lvl) or {}).get("witness"))
                     for lvl in levels})

        ok = _sig(dev) == _sig(f32) == _sig(host)
        if injected is not None:
            ok = (ok and dev.get("weakest-violated")
                  == weakest[injected])
        if not ok:
            entry = {"trial": t, "seed": s, "injected": injected,
                     "device": {"holds": dev.get("holds"),
                                "weakest": dev.get("weakest-violated"),
                                "engine": dev.get("engine")},
                     "f32": {"holds": f32.get("holds"),
                             "weakest": f32.get("weakest-violated"),
                             "engine": f32.get("engine")},
                     "host": {"holds": host.get("holds"),
                              "weakest": host.get("weakest-violated"),
                              "engine": host.get("engine")}}
            bad.append(entry)
            print(f"LATTICE MISMATCH {entry}", file=sys.stderr)
        if t % 25 == 24:
            print(f"lattice {t + 1}/{k} ok "
                  f"({time.monotonic() - t0:.0f}s)", flush=True)
    return bad


def word_trials(k: int, seed: int) -> list:
    """Word-packed post-hoc walk differential: ``k`` random register
    histories (the :func:`trial_params` mix — ragged concurrency,
    crashes, injected violations) checked with the word body FORCED
    (``JEPSEN_TPU_WORD_POSTHOC=1``) vs the dense body
    (``JEPSEN_TPU_NO_WORD_WALK=1``): verdicts and failing ops must be
    identical. Returns mismatch dicts (empty = clean)."""
    import random as _random

    from jepsen_tpu import fixtures, models
    from jepsen_tpu.checkers import reach
    from jepsen_tpu.history import index, pack

    rng = _random.Random(seed)
    bad = []
    t0 = time.monotonic()
    for t in range(k):
        s = rng.randrange(1 << 30)
        kind = rng.choice(("cas", "register"))
        n_ops = rng.randrange(60, 500)
        procs = rng.randrange(2, 9)
        h = fixtures.gen_history(kind, n_ops=n_ops, processes=procs,
                                 seed=s)
        if rng.random() < 0.5:
            try:
                h = fixtures.corrupt(h, seed=s)
            except ValueError:
                pass
        packed = pack(index(h))
        model = (models.cas_register() if kind == "cas"
                 else models.register())
        os.environ["JEPSEN_TPU_WORD_POSTHOC"] = "1"
        try:
            word = reach.check_packed(model, packed)
        finally:
            os.environ.pop("JEPSEN_TPU_WORD_POSTHOC", None)
        os.environ["JEPSEN_TPU_NO_WORD_WALK"] = "1"
        try:
            dense = reach.check_packed(model, packed)
        finally:
            os.environ.pop("JEPSEN_TPU_NO_WORD_WALK", None)
        ok = (word.get("valid") == dense.get("valid")
              and word.get("op") == dense.get("op"))
        if not ok:
            entry = {"trial": t, "seed": s, "kind": kind,
                     "word": {"valid": word.get("valid"),
                              "op": word.get("op"),
                              "engine": word.get("engine")},
                     "dense": {"valid": dense.get("valid"),
                               "op": dense.get("op"),
                               "engine": dense.get("engine")}}
            bad.append(entry)
            print(f"WORD MISMATCH {entry}", file=sys.stderr)
        if t % 50 == 49:
            print(f"word {t + 1}/{k} ok "
                  f"({time.monotonic() - t0:.0f}s)", flush=True)
    return bad


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--pallas", action="store_true",
                    help="also run the pallas kernel (interpret mode)")
    ap.add_argument("--tpu", action="store_true",
                    help="run the device engine on the real accelerator "
                         "(default: CPU — per-trial dispatch round-trips "
                         "over a tunneled device dominate otherwise)")
    ap.add_argument("--chunklock", type=int, default=0, metavar="K",
                    help="additionally run K engine-scale chunk-lockstep "
                         "trials vs the C++ WGL engine (real chip)")
    ap.add_argument("--txn", type=int, default=0, metavar="K",
                    help="additionally run K transactional-checker "
                         "trials (random list-append histories with "
                         "injected ww/wr/rw cycles; word-packed "
                         "closure vs f32 body vs host SCC every "
                         "trial)")
    ap.add_argument("--lattice", type=int, default=0, metavar="K",
                    help="additionally run K consistency-lattice "
                         "trials (random list-append histories with "
                         "injected lattice fixtures; per-level holds "
                         "+ anomalies + witnesses, word closure vs "
                         "f32 body vs host reference every trial)")
    ap.add_argument("--word", type=int, default=0, metavar="K",
                    help="additionally run K word-packed post-hoc "
                         "walk trials (forced word body vs dense "
                         "body; verdict + failing-op identity)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()

    if not args.tpu:
        import jax
        try:
            # a sitecustomize may pin another platform; env alone is not
            # enough (same dance as tests/conftest.py)
            jax.config.update("jax_platforms", "cpu")
        except Exception:                               # noqa: BLE001
            pass

    t0 = time.monotonic()
    from jepsen_tpu import obs
    with obs.capture() as cap:
        mismatches, invalid_seen = run_many(
            args.n, args.seed, pallas=args.pallas, verbose=args.verbose)
        ckl_bad: list = []
        if args.chunklock:
            ckl_bad = chunklock_trials(args.chunklock, args.seed + 99)
        txn_bad: list = []
        if args.txn:
            txn_bad = txn_trials(args.txn, args.seed + 777)
        lat_bad: list = []
        if args.lattice:
            lat_bad = lattice_trials(args.lattice, args.seed + 31337)
        word_bad: list = []
        if args.word:
            word_bad = word_trials(args.word, args.seed + 4242)
    # observability over the whole fuzz session: silent-degradation
    # counters (pallas → XLA downgrades, swallowed checker crashes,
    # lockstep → per-key fallbacks) become greppable output instead of
    # log noise; "no silent fallback occurred" is now assertable
    obs_counters = {k: v for k, v in sorted(cap.counters.items())
                    if k.startswith(("reach.", "engine.fallback.",
                                     "engine.skipped.",
                                     "checker.swallowed.",
                                     "lockstep.", "txn."))}
    print(json.dumps({
        "trials": args.n, "mismatches": len(mismatches),
        "invalid_histories": invalid_seen,
        "chunklock_trials": args.chunklock,
        "chunklock_mismatches": len(ckl_bad),
        "txn_trials": args.txn,
        "txn_mismatches": len(txn_bad),
        "lattice_trials": args.lattice,
        "lattice_mismatches": len(lat_bad),
        "word_trials": args.word,
        "word_mismatches": len(word_bad),
        "swallowed_checker_crashes": sum(
            v for k, v in cap.counters.items()
            if k.startswith("checker.swallowed.")),
        "obs": obs_counters,
        "elapsed_s": round(time.monotonic() - t0, 1)}))
    return 1 if (mismatches or ckl_bad or txn_bad or lat_bad
                 or word_bad) else 0


if __name__ == "__main__":
    sys.exit(main())
