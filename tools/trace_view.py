"""Summarize a ``trace.json`` / ``obs.jsonl`` produced by
:mod:`jepsen_tpu.obs` (bench runs, stored run dirs) without opening a
trace viewer: top spans by SELF time (span duration minus the duration
of its children — children are spans on the same thread whose interval
is contained in the parent's), spans grouped by mesh device (the
``device`` arg the mesh-lockstep dispatch/collect spans carry), the
engine-decision ledger as a fallback/selection table, and the
counters.

Also renders a per-request serve WATERFALL: point it at a saved
``GET /check/<id>`` response (or a daemon-persisted ``results.json``,
whose ``serve`` sub-object carries the same fields) and it prints the
admit→coalesce→walk→publish stage bars, the attributed device time,
and the stitched dispatcher trace.

Usage:
    python tools/trace_view.py trace.json [--top 15] [--json]
    python tools/trace_view.py store/<name>/latest/obs.jsonl
    python tools/trace_view.py check_response.json   # waterfall

Exit codes: 0 on success, 2 when the file cannot be parsed.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def self_times(spans: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Aggregate per span NAME: count, total wall duration, and total
    self time (duration minus directly-contained child spans on the
    same thread). O(n log n) per thread via a sweep over spans sorted
    by (start, -duration): a stack of open intervals attributes each
    child's duration to its nearest enclosing parent."""
    by_tid: Dict[Any, List[Dict[str, Any]]] = defaultdict(list)
    for s in spans:
        if "ts" in s and "dur" in s:
            by_tid[s.get("tid", 0)].append(s)
    agg: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"count": 0, "total_us": 0.0, "self_us": 0.0})
    for tid_spans in by_tid.values():
        tid_spans.sort(key=lambda s: (s["ts"], -s["dur"]))
        stack: List[Dict[str, Any]] = []    # open enclosing spans
        child_us: Dict[int, float] = {}     # id(span) -> children dur
        for s in tid_spans:
            while stack and stack[-1]["ts"] + stack[-1]["dur"] <= s["ts"]:
                stack.pop()
            if stack:
                parent = stack[-1]
                child_us[id(parent)] = child_us.get(id(parent), 0.0) \
                    + s["dur"]
            stack.append(s)
        for s in tid_spans:
            a = agg[s["name"]]
            a["count"] += 1
            a["total_us"] += s["dur"]
            a["self_us"] += max(0.0, s["dur"] - child_us.get(id(s), 0.0))
    return dict(agg)


def device_table(spans: List[Dict[str, Any]]
                 ) -> Dict[str, Dict[str, Any]]:
    """Spans grouped by the ``device`` arg the mesh-lockstep
    dispatch/collect spans carry: per-device span counts and wall, plus
    a per-name breakdown — how evenly the multi-queue scheduler spread
    groups over the mesh."""
    per: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        d = (s.get("args") or {}).get("device")
        if d is None:
            continue
        a = per.setdefault(f"dev{d}", {"count": 0, "total_us": 0.0,
                                       "put_us": 0.0, "fetch_us": 0.0,
                                       "names": defaultdict(int)})
        a["count"] += 1
        dur = float(s.get("dur", 0.0))
        a["total_us"] += dur
        name = s.get("name", "?")
        # put/fetch wall per device (the transfer-diet evidence): the
        # dispatch spans cover operand marshalling + program queueing,
        # the collect spans the verdict round-trip
        if "dispatch" in name:
            a["put_us"] += dur
        elif "collect" in name:
            a["fetch_us"] += dur
        a["names"][name] += 1
    return {k: {"count": int(v["count"]),
                "total_ms": round(v["total_us"] / 1e3, 3),
                "put_ms": round(v["put_us"] / 1e3, 3),
                "fetch_ms": round(v["fetch_us"] / 1e3, 3),
                "names": dict(v["names"])}
            for k, v in sorted(per.items())}


def decision_table(decisions: List[Dict[str, Any]]
                   ) -> Dict[str, Dict[str, int]]:
    """Ledger records grouped ``event -> "stage[/cause]" -> count``."""
    out: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    for d in decisions:
        key = str(d.get("stage", "?"))
        if d.get("cause"):
            key += f" / {d['cause']}"
        out[str(d.get("event", "?"))][key] += 1
    return {ev: dict(rows) for ev, rows in out.items()}


def request_waterfall(doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Extract the serve-request waterfall view from a
    ``GET /check/<id>`` response or a daemon-persisted
    ``results.json`` (its ``serve`` sub-object). None when the
    document carries no waterfall."""
    serve = doc.get("serve") if isinstance(doc.get("serve"),
                                           dict) else {}
    wf = doc.get("waterfall") or serve.get("waterfall")
    if not wf:
        return None
    src = doc if doc.get("waterfall") else serve
    return {
        "id": doc.get("id") or serve.get("id"),
        "tenant": doc.get("tenant") or serve.get("tenant"),
        "status": doc.get("status"),
        "latency_s": doc.get("latency-s") or serve.get("latency-s"),
        "device_s": doc.get("device-s") or serve.get("device-s"),
        "waterfall": wf,
        "trace": src.get("trace") or [],
    }


def _print_waterfall(w: Dict[str, Any], width: int = 44) -> None:
    total = max((s["start-s"] + s["dur-s"] for s in w["waterfall"]),
                default=0.0) or 1e-9
    head = f"request {w.get('id') or '?'}"
    if w.get("tenant"):
        head += f" (tenant {w['tenant']})"
    if w.get("status"):
        head += f" {w['status']}"
    if w.get("latency_s") is not None:
        head += f", {w['latency_s']:.4f}s end to end"
    if w.get("device_s") is not None:
        head += f", {w['device_s']:.6f}s device"
    print(head)
    for s in w["waterfall"]:
        lead = min(width, int(round(s["start-s"] / total * width)))
        bar = max(1, min(width + 1 - lead,
                         int(round(s["dur-s"] / total * width))))
        tail = width + 1 - lead - bar
        print(f"  {s['stage']:>9} {s['start-s']:>9.4f}s "
              f"{' ' * lead}{'#' * bar}{' ' * tail} "
              f"{s['dur-s']:.4f}s")
    if w["trace"]:
        print("  stitched dispatcher trace:")
        for r in w["trace"]:
            extra = {k: v for k, v in r.items()
                     if k not in ("stage", "event", "id", "ts")}
            print(f"    {r.get('event', '?'):9} "
                  f"{r.get('stage', '?'):16} "
                  f"{json.dumps(extra, default=str)}")


def summarize(path: str, top: int = 15) -> Dict[str, Any]:
    from jepsen_tpu import obs

    data = obs.load_any(path)
    st = self_times(data["spans"])
    ranked = sorted(st.items(), key=lambda kv: -kv[1]["self_us"])[:top]
    gauges = {g["name"]: g["value"] for g in data["gauges"]}
    out = {
        "file": path,
        "spans": len(data["spans"]),
        "top_spans_by_self_time": [
            {"name": name, "count": int(a["count"]),
             "total_ms": round(a["total_us"] / 1e3, 3),
             "self_ms": round(a["self_us"] / 1e3, 3)}
            for name, a in ranked],
        "decisions": decision_table(data["decisions"]),
        "counters": {c["name"]: c["value"] for c in data["counters"]},
        "gauges": gauges,
        "histograms": {h["name"]: obs.hist_summary(h)
                       for h in data.get("histograms", [])},
    }
    by_dev = device_table(data["spans"])
    if by_dev:
        out["spans_by_device"] = by_dev
    # transfer diet (ISSUE 5): wire bytes actually moved vs the
    # blanket int32/f32 format, and which fetch protocol answered
    counters = out["counters"]
    packed = counters.get("transfer.packed_bytes")
    if packed:
        unpacked = counters.get("transfer.unpacked_bytes", 0)
        out["transfer_diet"] = {
            "packed_bytes": int(packed),
            "unpacked_bytes": int(unpacked),
            "ratio": round(unpacked / max(packed, 1), 2),
            "fetch_lazy": int(counters.get("fetch.lazy", 0)),
            "fetch_eager": int(counters.get("fetch.eager", 0)),
            "donate_reuse": int(counters.get("donate.reuse", 0)),
        }
    # host/device overlap of the streaming prep pipeline (ISSUE 3):
    # hidden/wall is the fraction of host prep that cost no wall-clock
    wall = gauges.get("prep.wall_s")
    if isinstance(wall, (int, float)) and wall > 0:
        hidden = float(gauges.get("prep.hidden_s", 0) or 0)
        out["prep_overlap"] = {
            "mode": gauges.get("prep.mode"),
            "wall_s": wall,
            "hidden_s": hidden,
            "efficiency": round(hidden / wall, 3),
        }
    return out


def _print_human(s: Dict[str, Any]) -> None:
    print(f"{s['file']}: {s['spans']} spans")
    if s["top_spans_by_self_time"]:
        print("\ntop spans by self time:")
        print(f"  {'name':32} {'count':>6} {'self ms':>10} {'total ms':>10}")
        for row in s["top_spans_by_self_time"]:
            print(f"  {row['name']:32} {row['count']:>6} "
                  f"{row['self_ms']:>10.3f} {row['total_ms']:>10.3f}")
    if s.get("spans_by_device"):
        print("\nspans by device (mesh-lockstep dispatch/collect):")
        print(f"  {'device':8} {'spans':>5} {'total ms':>10} "
              f"{'put ms':>10} {'fetch ms':>10}")
        for dev, a in s["spans_by_device"].items():
            names = " ".join(f"{n}x{c}"
                             for n, c in sorted(a["names"].items()))
            print(f"  {dev:8} {a['count']:>5} {a['total_ms']:>10.3f} "
                  f"{a['put_ms']:>10.3f} {a['fetch_ms']:>10.3f}  "
                  f"{names}")
    if s.get("transfer_diet"):
        td = s["transfer_diet"]
        print(f"\ntransfer diet: {td['packed_bytes']} wire bytes "
              f"({td['ratio']}x under the blanket "
              f"{td['unpacked_bytes']}), fetches "
              f"lazy x{td['fetch_lazy']} / eager x{td['fetch_eager']}, "
              f"donated dispatches x{td['donate_reuse']}")
    if s.get("prep_overlap"):
        po = s["prep_overlap"]
        print(f"\nprep overlap ({po.get('mode')}): "
              f"{po['hidden_s']:.4f}s of {po['wall_s']:.4f}s host prep "
              f"hidden under device walks "
              f"(efficiency {po['efficiency']:.0%})")
    if s["decisions"]:
        print("\nengine-decision ledger:")
        for event, rows in sorted(s["decisions"].items()):
            print(f"  {event}:")
            for key, n in sorted(rows.items(), key=lambda kv: -kv[1]):
                print(f"    {key:48} x{n}")
    if s.get("histograms"):
        print("\nhistograms:")
        print(f"  {'name':32} {'count':>7} {'p50':>10} {'p99':>10} "
              f"{'mean':>10}")
        for name, h in sorted(s["histograms"].items()):
            print(f"  {name:32} {h.get('count', 0):>7} "
                  f"{h.get('p50') or 0:>10.4f} "
                  f"{h.get('p99') or 0:>10.4f} "
                  f"{h.get('mean') or 0:>10.4f}")
    if s["counters"]:
        print("\ncounters:")
        for name, v in sorted(s["counters"].items()):
            print(f"  {name:48} {v}")
    if s["gauges"]:
        print("\ngauges:")
        for name, v in sorted(s["gauges"].items()):
            print(f"  {name:48} {v}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="trace.json or obs.jsonl")
    ap.add_argument("--top", type=int, default=15,
                    help="spans to list (by self time)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args()
    # a /check/<id> response (or daemon results.json) renders as a
    # per-request waterfall instead of a span summary. The probe is
    # size-gated: waterfall docs are a few KB, while a full exported
    # trace.json can carry 100k spans — no point parsing those twice.
    try:
        if os.path.getsize(args.path) < (4 << 20):
            with open(args.path) as f:
                doc = json.load(f)
            if isinstance(doc, dict):
                w = request_waterfall(doc)
                if w is not None:
                    if args.json:
                        print(json.dumps(w))
                    else:
                        _print_waterfall(w)
                    return 0
    except (OSError, json.JSONDecodeError):
        pass                        # fall through to the span parser
    try:
        s = summarize(args.path, args.top)
    except (OSError, json.JSONDecodeError, KeyError, TypeError) as e:
        print(f"cannot parse {args.path}: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(s))
    else:
        _print_human(s)
    return 0


if __name__ == "__main__":
    sys.exit(main())
