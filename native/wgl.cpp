// Native Wing-Gong-Lowe linearizability search.
//
// Role of upstream knossos/src/knossos/wgl.clj + wgl/dll_history.clj
// (SURVEY.md §2.2): depth-first search over linearization orders with
// Lowe's memoization of <linearized-set, model-state> configurations.
// Independent implementation, C++ instead of Clojure/JVM:
//
// - a mutable doubly-linked list over unlinearized ops gives O(1)
//   lift/unlift during backtracking (upstream dll_history);
// - the memo set stores EXACT normalized keys (state, frontier pointer p,
//   mask words from p upward) — no fingerprint hashing, so no
//   probabilistic false-valid verdicts;
// - model semantics enter only through the dense transition table
//   precomputed by jepsen_tpu.models.memo (upstream model.memo): the
//   search never steps a model object;
// - crashed-op quotient (absent upstream — the "info ops are expensive"
//   2^k blowup): whenever the search fires a crashed (never-returning)
//   op, it fires the LOWEST unfired crashed entry with the same op id
//   instead. The lower twin is legal whenever the higher one is (its
//   invoke is earlier, so the Wing-Gong bound inv[j] < m is weaker) and
//   steps to the same state, and an exchange argument shows restricting
//   to lowest-first firings preserves completeness. Reachable masks are
//   therefore canonical by construction, so the memo collapses the
//   whole 2^k interchangeable class to its k+1 canonical members with
//   no key rewriting.
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).

#include <chrono>
#include <cstdint>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using u64 = std::uint64_t;
using i64 = std::int64_t;
using i32 = std::int32_t;

constexpr i64 INF = i64(1) << 60;

struct KeyHash {
    std::size_t operator()(const std::vector<u64>& v) const noexcept {
        u64 h = 1469598103934665603ull;            // FNV-1a
        for (u64 w : v) {
            h ^= w;
            h *= 1099511628211ull;
        }
        return static_cast<std::size_t>(h);
    }
};

struct Wgl {
    const i32* table;                              // [S, O] row-major
    i32 O = 0, n = 0;
    const i32* op_id = nullptr;
    std::vector<i64> inv, ret;
    std::vector<u64> mask;                         // linearized bitset
    std::vector<i32> nxt, prv;                     // dll; index n = head
    std::vector<u64> key_buf;
    std::unordered_set<std::vector<u64>, KeyHash> seen;
    // crashed-op quotient: entries sharing (crashed, op id), in entry
    // (= invocation) order; group_of[i] indexes groups, -1 = ungrouped
    std::vector<std::vector<i32>> groups;
    std::vector<i32> group_of;
    i64 explored = 0;
    i32 remaining_ok = 0;
    i32 total_ok = 0;
    i32 best_cover = -1;
    i32 best_stuck = -1;

    i32 step(i32 sid, i32 oid) const {
        return table[static_cast<i64>(sid) * O + oid];
    }

    void lift(i32 i) {                             // linearize i
        mask[i >> 6] |= u64(1) << (i & 63);
        nxt[prv[i]] = nxt[i];
        prv[nxt[i]] = prv[i];
    }

    void unlift(i32 i) {                           // backtrack
        mask[i >> 6] &= ~(u64(1) << (i & 63));
        nxt[prv[i]] = i;
        prv[nxt[i]] = i;
    }

    bool fired(i32 i) const {
        return (mask[i >> 6] >> (i & 63)) & 1;
    }

    // canonical member of a crashed pick's interchangeability class:
    // the lowest unfired twin (see header comment)
    i32 canonical_pick(i32 pick) const {
        i32 g = group_of[pick];
        if (g < 0) return pick;
        for (i32 m : groups[g])
            if (!fired(m)) return m;
        return pick;                               // unreachable: pick unfired
    }

    // Normalized memo key: every entry below p (the lowest unlinearized
    // one) is linearized in any config sharing p, so the key needs only
    // the words from p's word upward, trimmed of trailing zeros. Exact:
    // the full mask is reconstructible from (p, window).
    bool memo_insert(i32 sid, i32 p) {
        key_buf.clear();
        key_buf.push_back((static_cast<u64>(static_cast<std::uint32_t>(sid))
                           << 32) |
                          static_cast<u64>(static_cast<std::uint32_t>(p)));
        i32 wp = (p >= n ? n : p) >> 6;
        i32 wlast = static_cast<i32>(mask.size()) - 1;
        while (wlast > wp && mask[wlast] == 0) --wlast;
        for (i32 w = wp; w <= wlast; ++w) key_buf.push_back(mask[w]);
        return seen.insert(key_buf).second;
    }
};

}  // namespace

extern "C" {

// out[0] verdict: 1 valid, 0 invalid, -1 unknown
// out[1] stuck entry index (for invalid verdicts)
// out[2] max ok-ops linearized in any fully-explored config
// out[3] cause: 0 none, 1 timeout, 2 config-explosion, 3 aborted
//
// Failure evidence (knossos :final-paths analogue): with cfg_cap > 0,
// up to cfg_cap dead-end configurations at the DEEPEST cover are
// emitted as (cfg_sid[i], cfg_mask[i * mask_words .. +mask_words))
// where mask_words = (n + 63)/64 + 1 — the caller reconstructs model
// state and linearized-pending ops from the mask. *n_cfg receives the
// count. Collection resets whenever a deeper cover is reached, so the
// survivors are exactly the configurations the search was stuck at.
//
// returns configs explored
i64 wgl_check(const i32* table, i32 S, i32 O,
              const i32* inv_ev, const i64* ret_ev, const i32* op_id,
              const std::uint8_t* crashed, i32 n,
              i64 max_configs, double time_limit_s,
              const volatile i32* abort_flag, i32* out,
              i32 cfg_cap, i32* cfg_sid, u64* cfg_mask, i32* n_cfg) {
    (void)S;
    Wgl w;
    w.table = table;
    w.O = O;
    w.n = n;
    w.op_id = op_id;
    w.inv.resize(n);
    w.ret.resize(n);
    w.mask.assign(static_cast<std::size_t>(n + 63) / 64 + 1, 0);
    w.nxt.resize(n + 1);
    w.prv.resize(n + 1);
    for (i32 i = 0; i < n; ++i) {
        w.inv[i] = inv_ev[i];
        w.ret[i] = crashed[i] ? INF : ret_ev[i];
        if (!crashed[i]) ++w.total_ok;
        w.nxt[i] = i + 1;
        w.prv[i + 1] = i;
    }
    w.nxt[n] = 0;                                  // head sentinel
    w.prv[0] = n;
    w.remaining_ok = w.total_ok;
    w.group_of.assign(n, -1);
    {
        std::unordered_map<i32, i32> gid;          // op id -> group index
        for (i32 i = 0; i < n; ++i) {
            if (!crashed[i]) continue;
            auto it = gid.find(op_id[i]);
            if (it == gid.end()) {
                it = gid.emplace(op_id[i],
                                 static_cast<i32>(w.groups.size())).first;
                w.groups.emplace_back();
            }
            w.groups[it->second].push_back(i);     // ascending entry order
            w.group_of[i] = it->second;
        }
        for (i32 i = 0; i < n; ++i)                // singletons: no redirect
            if (w.group_of[i] >= 0 && w.groups[w.group_of[i]].size() < 2)
                w.group_of[i] = -1;
    }
    out[0] = 1;
    out[1] = -1;
    out[2] = 0;
    out[3] = 0;
    if (n_cfg) *n_cfg = 0;
    if (w.total_ok == 0) return 0;

    auto t0 = std::chrono::steady_clock::now();
    i32 cause = 0;
    auto over_budget = [&]() -> bool {
        if (abort_flag && *abort_flag) { cause = 3; return true; }
        if (time_limit_s > 0) {
            double el = std::chrono::duration<double>(
                std::chrono::steady_clock::now() - t0).count();
            if (el > time_limit_s) { cause = 1; return true; }
        }
        if (static_cast<i64>(w.seen.size()) > max_configs) {
            cause = 2;
            return true;
        }
        return false;
    };

    // Iterative DFS with undo. A frame's `chosen` is the entry that was
    // linearized to ENTER it (undone when the frame pops); `cursor`/`m`
    // hold its candidate scan: next dll entry to try, and the min return
    // time over entries already scanned (the Wing-Gong legality bound:
    // a candidate j is legal only while inv[j] < m).
    struct Frame {
        i32 sid;
        i32 chosen;
        i32 cursor;
        i64 m;
        i32 cover;
    };
    std::vector<Frame> stack;
    stack.push_back({0, -1, w.nxt[n], INF, 0});
    w.memo_insert(0, w.nxt[n]);
    i64 tick = 0;

    while (!stack.empty()) {
        Frame& f = stack.back();
        if ((tick++ & 255) == 0 && over_budget()) {
            out[0] = -1;
            out[3] = cause;
            return w.explored;
        }
        i32 j = f.cursor;
        i32 pick = -1, pick_sid = -1;
        while (j < n) {
            if (w.inv[j] >= f.m) break;
            i32 sid2 = w.step(f.sid, w.op_id[j]);
            i64 rj = w.ret[j];
            i32 jn = w.nxt[j];
            if (rj < f.m) f.m = rj;
            if (sid2 >= 0) {
                pick = j;
                pick_sid = sid2;
                f.cursor = jn;
                break;
            }
            j = jn;
        }
        if (pick < 0) {
            if (f.cover > w.best_cover) {
                w.best_cover = f.cover;
                i32 s = w.nxt[n];                  // lowest unlinearized ok
                while (s < n && w.ret[s] == INF) s = w.nxt[s];
                w.best_stuck = (s < n) ? s : w.nxt[n];
                if (n_cfg) *n_cfg = 0;             // deeper: restart evidence
            }
            if (cfg_cap > 0 && n_cfg && f.cover == w.best_cover
                && *n_cfg < cfg_cap) {
                const i64 words = static_cast<i64>(w.mask.size());
                cfg_sid[*n_cfg] = f.sid;
                for (i64 wd = 0; wd < words; ++wd)
                    cfg_mask[static_cast<i64>(*n_cfg) * words + wd] =
                        w.mask[static_cast<std::size_t>(wd)];
                ++*n_cfg;
            }
            i32 ch = f.chosen;
            stack.pop_back();
            if (ch >= 0) {
                w.unlift(ch);
                if (w.ret[ch] != INF) ++w.remaining_ok;
            }
            continue;
        }
        ++w.explored;
        if (w.ret[pick] == INF) pick = w.canonical_pick(pick);
        w.lift(pick);
        bool is_ok = (w.ret[pick] != INF);
        if (is_ok && --w.remaining_ok == 0) {
            out[0] = 1;
            out[2] = w.total_ok;
            return w.explored;
        }
        i32 child_cover = f.cover + (is_ok ? 1 : 0);
        i32 p = w.nxt[n];
        if (w.memo_insert(pick_sid, p)) {
            stack.push_back({pick_sid, pick, p, INF, child_cover});
        } else {
            w.unlift(pick);
            if (is_ok) ++w.remaining_ok;
        }
    }

    out[0] = 0;
    out[1] = (w.best_stuck >= 0) ? w.best_stuck : w.nxt[n];
    out[2] = (w.best_cover >= 0) ? w.best_cover : 0;
    return w.explored;
}

}  // extern "C"
