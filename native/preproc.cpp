// Native event-stream preprocessing for the device reachability engine.
//
// The device walk consumes flat int arrays (jepsen_tpu/checkers/events.py);
// building them involves two inherently-sequential scans that are the
// host-side hot path on 100k-op histories:
//
//   1. slot assignment: lowest-free-slot seat assignment over the sorted
//      invoke/return event stream (interval-graph greedy coloring — the
//      packed-config representation upstream keeps in
//      knossos/src/knossos/linear/config.clj [U]);
//   2. the returns-only projection with per-return pending-op snapshots.
//
// Python loops cost ~0.4 s at 147k events — comparable to the whole
// device walk after the Pallas kernel; here they are ~2 ms. Built on
// demand with g++ like native/wgl.cpp; the Python implementations remain
// as fallback.

#include <algorithm>
#include <cstdint>
#include <deque>
#include <queue>
#include <unordered_map>
#include <vector>
#include <functional>

extern "C" {

// Events must be pre-sorted by rank. kind: 0 = invoke, 1 = return.
// entry[e] is the analysis-entry index of event e. Writes out_slot[E];
// returns the number of slots used (W), or -1 if it would exceed
// max_slots.
int64_t jt_assign_slots(int64_t E, const int32_t* kind,
                        const int32_t* entry, int64_t n_entries,
                        int32_t max_slots, int32_t* out_slot) {
    std::priority_queue<int32_t, std::vector<int32_t>,
                        std::greater<int32_t>> free_slots;
    std::vector<int32_t> slot_of(static_cast<size_t>(n_entries), -1);
    int32_t hi = 0;
    for (int64_t e = 0; e < E; ++e) {
        if (kind[e] == 0) {
            int32_t s;
            if (!free_slots.empty()) {
                s = free_slots.top();
                free_slots.pop();
            } else {
                s = hi++;
                if (hi > max_slots) return -1;
            }
            slot_of[static_cast<size_t>(entry[e])] = s;
            out_slot[e] = s;
        } else {
            int32_t s = slot_of[static_cast<size_t>(entry[e])];
            out_slot[e] = s;
            free_slots.push(s);
        }
    }
    return hi;
}

// Project the event stream to its return events. Writes ret_slot[R],
// slot_ops[R*W] (the full pending map at each return, -1 = free),
// ret_event[R], ret_entry[R]; returns R (the number of returns).
int64_t jt_returns_view(int64_t E, const int32_t* kind,
                        const int32_t* slot, const int32_t* opid,
                        const int32_t* entry, int32_t W,
                        int32_t* ret_slot, int32_t* slot_ops,
                        int32_t* ret_event, int32_t* ret_entry) {
    std::vector<int32_t> cur(static_cast<size_t>(W), -1);
    int64_t r = 0;
    for (int64_t e = 0; e < E; ++e) {
        if (kind[e] == 0) {                       // invoke
            cur[static_cast<size_t>(slot[e])] = opid[e];
        } else if (kind[e] == 1) {                // return
            int32_t s = slot[e];
            for (int32_t w = 0; w < W; ++w)
                slot_ops[r * W + w] = cur[static_cast<size_t>(w)];
            ret_slot[r] = s;
            ret_event[r] = static_cast<int32_t>(e);
            ret_entry[r] = entry[e];
            cur[static_cast<size_t>(s)] = -1;
            ++r;
        }
    }
    return r;
}

// Batched per-key event building for the keyed (`independent`) batch
// checker: ONE call replaces, for every key at once, the per-key
// event-sort + noop-crash drop + slot assignment + returns projection
// that cost ~1.3 s of Python/ctypes plumbing at 4096 keys.
//
// Inputs are the keys' packed entry arrays concatenated (entry_off[K+1]
// offsets): inv_rank / ret_rank (ret_rank < 0 = crashed, forever
// pending), opid already remapped into the UNION alphabet, and the
// union-level noop flags (crashed entries whose op is a no-op in every
// state are provably irrelevant and dropped, as in events.build).
//
// Outputs (flat over all keys, preallocated by the caller):
//   ret_slot[R_total], slot_ops[R_total * w_cap] (-1 = free slot),
//   pend[R_total] (pending count incl. the returning op — the gate
//   ladder's exact pass bound), key_W[K] (slots used; -1 = overflow
//   beyond max_slots), key_R[K] (returns emitted), ret_entry[R_total]
//   (LOCAL entry index within the key, for failure reporting).
// Returns R_total.
int64_t jt_build_keyed(int64_t K, const int64_t* entry_off,
                       const int32_t* inv_rank, const int32_t* ret_rank,
                       const int32_t* opid, const uint8_t* crashed,
                       const uint8_t* noop_op, int32_t max_slots,
                       int32_t w_cap,
                       int32_t* ret_slot, int32_t* slot_ops,
                       int32_t* pend, int32_t* key_W, int32_t* key_R,
                       int32_t* ret_entry) {
    struct Ev { int32_t rank; int32_t entry; uint8_t is_ret; };
    std::vector<Ev> evs;
    std::vector<int32_t> slot_of, cur;
    int64_t r_out = 0;
    for (int64_t k = 0; k < K; ++k) {
        const int64_t lo = entry_off[k], hi = entry_off[k + 1];
        const int64_t n = hi - lo;
        evs.clear();
        evs.reserve(static_cast<size_t>(2 * n));
        for (int64_t i = lo; i < hi; ++i) {
            const bool crash = crashed[i] != 0;
            if (crash && noop_op[opid[i]]) continue;    // droppable
            const int32_t e = static_cast<int32_t>(i - lo);
            evs.push_back({inv_rank[i], e, 0});
            if (!crash) evs.push_back({ret_rank[i], e, 1});
        }
        std::sort(evs.begin(), evs.end(),
                  [](const Ev& a, const Ev& b) { return a.rank < b.rank; });
        slot_of.assign(static_cast<size_t>(n), -1);
        cur.assign(static_cast<size_t>(w_cap), -1);
        std::priority_queue<int32_t, std::vector<int32_t>,
                            std::greater<int32_t>> free_slots;
        int32_t hi_slot = 0, n_pend = 0, n_ret = 0;
        bool overflow = false;
        const int64_t r_base = r_out;
        for (const Ev& ev : evs) {
            if (!ev.is_ret) {                           // invoke
                int32_t s;
                if (!free_slots.empty()) {
                    s = free_slots.top();
                    free_slots.pop();
                } else {
                    s = hi_slot++;
                    if (hi_slot > max_slots || hi_slot > w_cap) {
                        overflow = true;
                        break;
                    }
                }
                slot_of[static_cast<size_t>(ev.entry)] = s;
                cur[static_cast<size_t>(s)] = opid[lo + ev.entry];
                ++n_pend;
            } else {                                    // return
                const int32_t s = slot_of[static_cast<size_t>(ev.entry)];
                int32_t* row = slot_ops + (r_base + n_ret) * w_cap;
                for (int32_t w = 0; w < w_cap; ++w) row[w] = cur[w];
                ret_slot[r_base + n_ret] = s;
                pend[r_base + n_ret] = n_pend;
                ret_entry[r_base + n_ret] = ev.entry;
                cur[static_cast<size_t>(s)] = -1;
                free_slots.push(s);
                --n_pend;
                ++n_ret;
            }
        }
        if (overflow) {
            key_W[k] = -1;
            key_R[k] = 0;
            continue;                   // r_out unchanged: rows reused
        }
        key_W[k] = hi_slot;
        key_R[k] = n_ret;
        r_out = r_base + n_ret;
    }
    return r_out;
}

// Dense-reachability returns walk on bit-packed config sets — the
// online monitor's host-side engine (jepsen_tpu/checkers/online.py).
// The config set R[s] is a bitset over pending-set masks m (bit m of
// word m/64), one row per model state: a few words total at monitor
// scale, so word-parallel C++ beats both the per-return NumPy fixpoint
// (~170 us/return) and a jitted XLA CPU walk (~19 us/return + ~ms of
// dispatch per flush) by orders of magnitude.
//
// Semantics match reach._walk_returns / online._walk_return exactly:
// per return, Gauss-Seidel fire passes to the fixpoint (firing slot j
// maps configs with mask-bit j clear into their transition images with
// bit j set), then projection on the returning slot (keep configs that
// fired it, clearing the bit). Returns the index of the first return
// that emptied the set, or -1; R is updated in place (on death it
// holds the empty set).
int64_t jt_walk_dense(int32_t S, int32_t W, int64_t n_words,
                      const int32_t* T, int32_t n_ops,
                      uint64_t* R,
                      int64_t L, const int32_t* ret_slot,
                      const int32_t* rows) {
    const int64_t M_bits = n_words * 64;
    // clear_mask[j][w]: bit m set iff mask m has slot-bit j CLEAR
    std::vector<uint64_t> clear_mask(static_cast<size_t>(W) * n_words);
    for (int32_t j = 0; j < W; ++j) {
        const int64_t bitj = int64_t(1) << j;
        for (int64_t w = 0; w < n_words; ++w) {
            uint64_t v = 0;
            for (int b = 0; b < 64; ++b) {
                const int64_t m = w * 64 + b;
                if (m < M_bits && !(m & bitj)) v |= uint64_t(1) << b;
            }
            clear_mask[static_cast<size_t>(j) * n_words + w] = v;
        }
    }
    std::vector<uint64_t> src(static_cast<size_t>(n_words));
    std::vector<uint64_t> tmp(static_cast<size_t>(S) * n_words);
    for (int64_t r = 0; r < L; ++r) {
        // fire to fixpoint (Gauss-Seidel in place; monotone)
        bool changed = true;
        while (changed) {
            changed = false;
            for (int32_t j = 0; j < W; ++j) {
                const int32_t o = rows[r * W + j];
                if (o < 0) continue;
                const int64_t bitj = int64_t(1) << j;
                const int64_t w_off = bitj >> 6;
                const int b_off = static_cast<int>(bitj & 63);
                const uint64_t* cm =
                    &clear_mask[static_cast<size_t>(j) * n_words];
                for (int32_t s = 0; s < S; ++s) {
                    const int32_t t = T[s * n_ops + o];
                    if (t < 0) continue;
                    uint64_t* Rs = R + s * n_words;
                    uint64_t* Rt = R + t * n_words;
                    for (int64_t w = 0; w < n_words; ++w)
                        src[static_cast<size_t>(w)] = Rs[w] & cm[w];
                    // OR the src bits shifted UP by bitj into Rt
                    for (int64_t w = n_words - 1; w >= w_off; --w) {
                        uint64_t v = src[static_cast<size_t>(w - w_off)]
                                     << b_off;
                        if (b_off && w - w_off - 1 >= 0)
                            v |= src[static_cast<size_t>(w - w_off - 1)]
                                 >> (64 - b_off);
                        if (v & ~Rt[w]) {
                            Rt[w] |= v;
                            changed = true;
                        }
                    }
                }
            }
        }
        // projection on the returning slot
        const int32_t jr = ret_slot[r];
        if (jr >= 0) {
            const int64_t bitj = int64_t(1) << jr;
            const int64_t w_off = bitj >> 6;
            const int b_off = static_cast<int>(bitj & 63);
            const uint64_t* cm =
                &clear_mask[static_cast<size_t>(jr) * n_words];
            bool any = false;
            for (int32_t s = 0; s < S; ++s) {
                const uint64_t* Rs = R + s * n_words;
                uint64_t* out = &tmp[static_cast<size_t>(s) * n_words];
                for (int64_t w = 0; w < n_words; ++w) {
                    const int64_t wh = w + w_off;
                    uint64_t kept_lo = 0, kept_hi = 0;
                    if (wh < n_words) kept_lo = Rs[wh] & ~cm[wh];
                    if (b_off && wh + 1 < n_words)
                        kept_hi = Rs[wh + 1] & ~cm[wh + 1];
                    uint64_t v = kept_lo >> b_off;
                    if (b_off) v |= kept_hi << (64 - b_off);
                    out[w] = v;
                    any |= (v != 0);
                }
            }
            std::copy(tmp.begin(),
                      tmp.begin() + static_cast<size_t>(S) * n_words, R);
            if (!any) return r;
        }
    }
    return -1;
}

// Benchmark history generator (fixtures.gen_packed): the tick-loop
// simulation of fixtures.gen_history for the register/cas kinds,
// emitting packed per-entry arrays directly — no Python Op objects, so
// a 10M-op benchmark input builds in well under a second instead of
// ~4 minutes. Linearizable by construction exactly like the Python
// generator: each op commits atomically at a random instant between
// its invocation and response; failed CAS attempts are dropped (the
// post-hoc analysis strips them), and their event ranks stay consumed
// so real-time ordering matches a full history's.
//
// Op identity encoding (decoded by fixtures.gen_packed):
//   read observing None -> 0; read observing v -> 1 + v;
//   write v -> 1 + V + v; cas [a, b] -> 1 + 2V + a*V + b.
// Returns the number of entries written (<= n_ops).

namespace {
struct SplitMix64 {
    std::uint64_t s;
    explicit SplitMix64(std::uint64_t seed) : s(seed) {}
    std::uint64_t next() {
        std::uint64_t z = (s += 0x9E3779B97F4A7C15ull);
        z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
        z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
        return z ^ (z >> 31);
    }
    // uniform in [0, n) (n < 2^31; modulo bias is irrelevant here)
    int64_t below(int64_t n) { return static_cast<int64_t>(next() % n); }
    double unit() { return (next() >> 11) * 0x1.0p-53; }
};
}  // namespace

int64_t jt_gen_history(int64_t seed, int64_t n_ops, int32_t processes,
                       int32_t values, int32_t kind,  // 0=register 1=cas
                       int32_t* inv_ev, int32_t* ret_ev, int32_t* opid,
                       int32_t* proc) {
    SplitMix64 rng(static_cast<std::uint64_t>(seed) * 0x9E3779B97F4A7C15ull
                   + 0x243F6A8885A308D3ull);
    const int32_t V = values;
    struct Pend {
        int32_t stage = 0;      // 0 idle, 1 invoked, 2 committed
        int32_t inv_rank = 0;
        int32_t oid = 0;        // identity (read identity set at commit)
        bool okay = true;
    };
    std::vector<Pend> pend(static_cast<std::size_t>(processes));
    int32_t reg = -1;                                  // None
    int64_t invoked = 0, out = 0;
    int32_t ev = 0;
    int64_t live = 0;
    while (invoked < n_ops || live > 0) {
        const int64_t p = rng.below(processes);
        Pend& st = pend[static_cast<std::size_t>(p)];
        if (st.stage == 0) {
            if (invoked >= n_ops) continue;
            // choose an op (identity finalized at commit for reads)
            const double r = rng.unit();
            if (kind == 1 ? (r < 0.34) : (r < 0.5)) {
                st.oid = -1;                           // read, value TBD
            } else if (kind == 1 && r >= 0.67) {
                const int32_t a = static_cast<int32_t>(rng.below(V));
                const int32_t b = static_cast<int32_t>(rng.below(V));
                st.oid = 1 + 2 * V + a * V + b;
            } else {
                const int32_t v = static_cast<int32_t>(rng.below(V));
                st.oid = 1 + V + v;
            }
            st.inv_rank = ev++;
            st.stage = 1;
            ++invoked;
            ++live;
        } else if (st.stage == 1) {
            // commit atomically against the live register
            st.okay = true;
            if (st.oid == -1) {                        // read
                st.oid = (reg < 0) ? 0 : 1 + reg;
            } else if (st.oid >= 1 + 2 * V) {          // cas
                const int32_t enc = st.oid - (1 + 2 * V);
                const int32_t a = enc / V, b = enc % V;
                if (reg == a) reg = b;
                else st.okay = false;
            } else {                                   // write
                reg = st.oid - (1 + V);
            }
            st.stage = 2;
        } else {
            const int32_t rr = ev++;
            if (st.okay) {                             // failed ops drop
                inv_ev[out] = st.inv_rank;
                ret_ev[out] = rr;
                opid[out] = st.oid;
                proc[out] = static_cast<int32_t>(p);
                ++out;
            }
            st.stage = 0;
            --live;
        }
    }
    return out;
}

// ---------------------------------------------------------------------------
// Streaming monitor core (jepsen_tpu/checkers/online.py NativeStreamEngine):
// the per-op bookkeeping of the incremental linearizability monitor —
// slot assignment, settle-queue snapshots, and the settled-returns walk —
// in C++, fed in per-flush BATCHES. Profiling showed the monitor's cost
// was ~95% Python object churn (per-return snapshot lists, per-member
// interning, per-op dict traffic) and ~5% actual walking; this moves the
// churn to C++ and leaves Python only value interning (model-dependent)
// and the carried config set R (re-encoded on memo growth).
//
// Semantics mirror online.IncrementalEngine exactly (differential-tested):
//   invoke  -> lowest-free-slot binding (error on overflow/double invoke)
//   ok      -> settle item {binding, live-snapshot, crashed-count};
//              slot freed after the snapshot
//   fail    -> stripped (slot freed, never walked)
//   info    -> crashed: binding holds its slot forever, joins every later
//              return's pending map via the crashed-count prefix
// An item settles when every snapshot member has resolved; settled items
// are walked through jt_walk_dense in one batch per advance call.

namespace {

struct JtBind {
    int32_t slot;
    int8_t status;      // 0 pending, 1 ok, 2 fail, 3 crashed
    int32_t oid;        // resolved transition id (alphabet, append-only)
    int32_t wild;       // wildcard id for the unsettled-tail alarm
};

struct JtItem {
    int32_t b;          // returning binding index
    int32_t ncr;        // crashed-list length at feed time
    int32_t snap_off;   // into snap_pool
    int32_t snap_len;
};

struct JtMonitor {
    int32_t max_slots;
    int32_t W = 1;
    std::priority_queue<int32_t, std::vector<int32_t>,
                        std::greater<int32_t>> free_slots;
    int32_t hi = 0;
    std::vector<JtBind> binds;
    std::unordered_map<int64_t, int32_t> live;   // proc -> bind index
    std::vector<int32_t> crashed;                // bind indices
    std::deque<JtItem> queue;
    std::vector<int32_t> snap_pool;
    int64_t settled = 0;

    bool rows_for(const JtItem& it, int32_t* rows, bool wildcards) const {
        // materialize the item's pending map; returns false when a
        // member is unresolved (not settleable) unless wildcards
        // (tail-alarm mode: unresolved walks as crashed-at-invoke)
        for (int32_t j = 0; j < W; ++j) rows[j] = -1;
        for (int32_t k = 0; k < it.snap_len; ++k) {
            const JtBind& x = binds[static_cast<size_t>(
                snap_pool[static_cast<size_t>(it.snap_off) + k])];
            if (x.status == 0) {
                if (!wildcards) return false;
                rows[x.slot] = x.wild;
                continue;
            }
            if (x.status == 2) continue;             // fail: stripped
            rows[x.slot] = x.oid;
        }
        for (int32_t k = 0; k < it.ncr; ++k) {
            const JtBind& x = binds[static_cast<size_t>(crashed[k])];
            rows[x.slot] = x.oid;
        }
        const JtBind& rb = binds[static_cast<size_t>(it.b)];
        rows[rb.slot] = rb.oid;
        return true;
    }
};

}  // namespace

void* jt_mon_new(int32_t max_slots) {
    auto* m = new JtMonitor();
    m->max_slots = max_slots;
    return m;
}

void jt_mon_free(void* h) { delete static_cast<JtMonitor*>(h); }

// Feed a batch of ops. type: 0 invoke, 1 ok, 2 fail, 3 info; oid[i] is
// the resolved transition id for ok/info, the WILDCARD id for invoke
// (used only by the tail alarm), -1 for fail. The caller has already
// dropped nemesis ops and completions without a live invoke. Returns
// the (possibly grown) slot width W, or -1 on double invoke, -2 on
// slot overflow — both permanent-fallback conditions for the caller.
int64_t jt_mon_feed(void* h, int64_t n, const int32_t* type,
                    const int64_t* proc, const int32_t* oid) {
    auto* m = static_cast<JtMonitor*>(h);
    for (int64_t i = 0; i < n; ++i) {
        const int64_t p = proc[i];
        switch (type[i]) {
        case 0: {                                    // invoke
            if (m->live.count(p)) return -1;
            int32_t slot;
            if (!m->free_slots.empty()) {
                slot = m->free_slots.top();
                m->free_slots.pop();
            } else {
                slot = m->hi++;
            }
            if (slot >= m->max_slots) return -2;
            if (slot >= m->W) m->W = slot + 1;
            m->live[p] = static_cast<int32_t>(m->binds.size());
            m->binds.push_back({slot, 0, -1, oid[i]});
            break;
        }
        case 1: {                                    // ok
            auto it = m->live.find(p);
            if (it == m->live.end()) break;
            const int32_t bi = it->second;
            m->live.erase(it);
            JtBind& b = m->binds[static_cast<size_t>(bi)];
            b.status = 1;
            b.oid = oid[i];
            const int32_t off =
                static_cast<int32_t>(m->snap_pool.size());
            for (const auto& kv : m->live)
                m->snap_pool.push_back(kv.second);
            m->queue.push_back({bi,
                                static_cast<int32_t>(m->crashed.size()),
                                off,
                                static_cast<int32_t>(
                                    m->snap_pool.size()) - off});
            m->free_slots.push(b.slot);
            break;
        }
        case 2: {                                    // fail: stripped
            auto it = m->live.find(p);
            if (it == m->live.end()) break;
            JtBind& b = m->binds[static_cast<size_t>(it->second)];
            m->live.erase(it);
            b.status = 2;
            m->free_slots.push(b.slot);
            break;
        }
        case 3: {                                    // info: crashed
            auto it = m->live.find(p);
            if (it == m->live.end()) break;
            const int32_t bi = it->second;
            m->live.erase(it);
            JtBind& b = m->binds[static_cast<size_t>(bi)];
            b.status = 3;
            b.oid = oid[i];
            m->crashed.push_back(bi);                // slot held forever
            break;
        }
        default:
            break;
        }
    }
    return m->W;
}

// Walk every currently-settleable queued return through jt_walk_dense,
// dequeuing them. R is the carried config set, bit-packed
// [S, n_words] with M = 2^W mask bits, updated in place. Returns the
// number of returns walked; *out_dead_bind is the violating binding
// index (walking stopped there) or -1.
int64_t jt_mon_advance(void* h, const int32_t* T, int32_t S,
                       int32_t n_ops, uint64_t* R, int64_t n_words,
                       int32_t* out_dead_bind) {
    auto* m = static_cast<JtMonitor*>(h);
    *out_dead_bind = -1;
    std::vector<int32_t> rows;
    std::vector<int32_t> slots;
    std::vector<int32_t> bind_of;
    std::vector<int32_t> one(static_cast<size_t>(m->W));
    while (!m->queue.empty()) {
        const JtItem& it = m->queue.front();
        if (!m->rows_for(it, one.data(), false)) break;
        rows.insert(rows.end(), one.begin(), one.end());
        slots.push_back(m->binds[static_cast<size_t>(it.b)].slot);
        bind_of.push_back(it.b);
        m->queue.pop_front();
    }
    if (slots.empty()) return 0;
    const int64_t L = static_cast<int64_t>(slots.size());
    const int64_t dead = jt_walk_dense(S, m->W, n_words, T, n_ops, R,
                                       L, slots.data(), rows.data());
    if (dead >= 0) {
        *out_dead_bind = bind_of[static_cast<size_t>(dead)];
        m->settled += dead + 1;
        return dead + 1;
    }
    m->settled += L;
    return L;
}

// Pop every currently-settleable queued return WITHOUT walking it:
// fills rows [cap, W], slots [cap], binds [cap]; returns the count.
// The device-resident session engine drains here and walks the block
// on the accelerator (jepsen_tpu/serve/session.py) — the settle
// discipline stays this monitor's, only the walk moves off-host.
// The native settled counter advances for every POPPED item (on a
// mid-block death the engine's own Python counter — which stops at
// the death index — is the authoritative one); DEATH handling is
// entirely the caller's.
int64_t jt_mon_drain(void* h, int64_t cap, int32_t* rows,
                     int32_t* slots, int32_t* binds_out) {
    auto* m = static_cast<JtMonitor*>(h);
    int64_t n = 0;
    while (!m->queue.empty() && n < cap) {
        const JtItem& it = m->queue.front();
        if (!m->rows_for(it, rows + n * m->W, false)) break;
        slots[n] = m->binds[static_cast<size_t>(it.b)].slot;
        binds_out[n] = it.b;
        m->queue.pop_front();
        ++n;
    }
    m->settled += n;
    return n;
}

// Export the first K unsettled queue items for the tail alarm
// (unresolved members as their crashed-at-invoke wildcards). Fills
// rows [K, W], slots [K], binds [K]; returns the count.
int64_t jt_mon_tail(void* h, int64_t K, int32_t* rows, int32_t* slots,
                    int32_t* binds_out) {
    auto* m = static_cast<JtMonitor*>(h);
    int64_t n = 0;
    for (const JtItem& it : m->queue) {
        if (n >= K) break;
        m->rows_for(it, rows + n * m->W, true);
        slots[n] = m->binds[static_cast<size_t>(it.b)].slot;
        binds_out[n] = it.b;
        ++n;
    }
    return n;
}

// out[0] = settled returns, out[1] = queued (unsettled) returns,
// out[2] = live invocations, out[3] = current W, out[4] = 1 iff the
// queue FRONT is settleable (advance would walk at least one return —
// settleability is front-blocking, so callers can skip the R
// pack/unpack round trip when this is 0).
int64_t jt_mon_stats(void* h, int64_t* out) {
    auto* m = static_cast<JtMonitor*>(h);
    out[0] = m->settled;
    out[1] = static_cast<int64_t>(m->queue.size());
    out[2] = static_cast<int64_t>(m->live.size());
    out[3] = m->W;
    out[4] = 0;
    if (!m->queue.empty()) {
        std::vector<int32_t> one(static_cast<size_t>(m->W));
        out[4] = m->rows_for(m->queue.front(), one.data(), false) ? 1 : 0;
    }
    return 0;
}

// Live (still-pending) bindings: fills procs/binds up to cap; returns
// the count (the run-over path resolves these as crashed).
int64_t jt_mon_live(void* h, int64_t cap, int64_t* procs,
                    int32_t* binds_out) {
    auto* m = static_cast<JtMonitor*>(h);
    int64_t n = 0;
    for (const auto& kv : m->live) {
        if (n >= cap) break;
        procs[n] = kv.first;
        binds_out[n] = kv.second;
        ++n;
    }
    return n;
}

}  // extern "C"
