// Native event-stream preprocessing for the device reachability engine.
//
// The device walk consumes flat int arrays (jepsen_tpu/checkers/events.py);
// building them involves two inherently-sequential scans that are the
// host-side hot path on 100k-op histories:
//
//   1. slot assignment: lowest-free-slot seat assignment over the sorted
//      invoke/return event stream (interval-graph greedy coloring — the
//      packed-config representation upstream keeps in
//      knossos/src/knossos/linear/config.clj [U]);
//   2. the returns-only projection with per-return pending-op snapshots.
//
// Python loops cost ~0.4 s at 147k events — comparable to the whole
// device walk after the Pallas kernel; here they are ~2 ms. Built on
// demand with g++ like native/wgl.cpp; the Python implementations remain
// as fallback.

#include <cstdint>
#include <queue>
#include <vector>
#include <functional>

extern "C" {

// Events must be pre-sorted by rank. kind: 0 = invoke, 1 = return.
// entry[e] is the analysis-entry index of event e. Writes out_slot[E];
// returns the number of slots used (W), or -1 if it would exceed
// max_slots.
int64_t jt_assign_slots(int64_t E, const int32_t* kind,
                        const int32_t* entry, int64_t n_entries,
                        int32_t max_slots, int32_t* out_slot) {
    std::priority_queue<int32_t, std::vector<int32_t>,
                        std::greater<int32_t>> free_slots;
    std::vector<int32_t> slot_of(static_cast<size_t>(n_entries), -1);
    int32_t hi = 0;
    for (int64_t e = 0; e < E; ++e) {
        if (kind[e] == 0) {
            int32_t s;
            if (!free_slots.empty()) {
                s = free_slots.top();
                free_slots.pop();
            } else {
                s = hi++;
                if (hi > max_slots) return -1;
            }
            slot_of[static_cast<size_t>(entry[e])] = s;
            out_slot[e] = s;
        } else {
            int32_t s = slot_of[static_cast<size_t>(entry[e])];
            out_slot[e] = s;
            free_slots.push(s);
        }
    }
    return hi;
}

// Project the event stream to its return events. Writes ret_slot[R],
// slot_ops[R*W] (the full pending map at each return, -1 = free),
// ret_event[R], ret_entry[R]; returns R (the number of returns).
int64_t jt_returns_view(int64_t E, const int32_t* kind,
                        const int32_t* slot, const int32_t* opid,
                        const int32_t* entry, int32_t W,
                        int32_t* ret_slot, int32_t* slot_ops,
                        int32_t* ret_event, int32_t* ret_entry) {
    std::vector<int32_t> cur(static_cast<size_t>(W), -1);
    int64_t r = 0;
    for (int64_t e = 0; e < E; ++e) {
        if (kind[e] == 0) {                       // invoke
            cur[static_cast<size_t>(slot[e])] = opid[e];
        } else if (kind[e] == 1) {                // return
            int32_t s = slot[e];
            for (int32_t w = 0; w < W; ++w)
                slot_ops[r * W + w] = cur[static_cast<size_t>(w)];
            ret_slot[r] = s;
            ret_event[r] = static_cast<int32_t>(e);
            ret_entry[r] = entry[e];
            cur[static_cast<size_t>(s)] = -1;
            ++r;
        }
    }
    return r;
}

}  // extern "C"
